"""Trajectory postprocessing math: GAE and V-trace as jitted scans.

Reference analogs: GAE in rllib (general_advantage_estimation learner
connector, rllib/connectors/learner/...) and V-trace
(rllib/algorithms/impala/vtrace.py, from IMPALA, Espeholt et al. 2018).
Both are reverse-time recurrences — expressed here as `lax.scan` over
the time axis so they compile into the learner's XLA program instead of
running as Python/numpy loops on the host.

All inputs are time-major [T, B].
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


@jax.jit
def compute_gae(
    rewards: jax.Array,       # [T, B]
    values: jax.Array,        # [T, B] V(s_t)
    final_values: jax.Array,  # [B]    V(s_T) bootstrap
    terminateds: jax.Array,   # [T, B] true episode ends (no bootstrap)
    truncateds: jax.Array,    # [T, B] time-limit ends (bootstrap through)
    gamma: float = 0.99,
    lam: float = 0.95,
):
    """Returns (advantages [T, B], value_targets [T, B]).

    delta_t = r_t + gamma * V(s_{t+1}) * (1 - done) - V(s_t)
    A_t     = delta_t + gamma * lam * (1 - done) * A_{t+1}
    """
    next_values = jnp.concatenate([values[1:], final_values[None]], axis=0)
    # At an episode boundary (termination OR truncation) the stored
    # next_value belongs to the *new* episode's first obs (autoreset), so
    # the bootstrap is zeroed and the recurrence cut at both. For
    # truncations this under-bootstraps the final step (the unbiased fix
    # needs V(final_obs), which autoreset discards); zero is the standard
    # bounded-bias choice.
    cut = 1.0 - (
        terminateds.astype(bool) | truncateds.astype(bool)
    ).astype(jnp.float32)
    deltas = rewards + gamma * next_values * cut - values

    def scan_fn(carry, xs):
        delta, c = xs
        adv = delta + gamma * lam * c * carry
        return adv, adv

    _, advs = lax.scan(scan_fn, jnp.zeros_like(final_values), (deltas, cut), reverse=True)
    return advs, advs + values


@jax.jit
def compute_vtrace(
    behaviour_logp: jax.Array,  # [T, B] logp of actions under the actor policy
    target_logp: jax.Array,     # [T, B] logp under the learner policy
    rewards: jax.Array,         # [T, B]
    values: jax.Array,          # [T, B] V(s_t) under learner
    final_values: jax.Array,    # [B]
    terminateds: jax.Array,     # [T, B]
    truncateds: jax.Array = None,  # [T, B] time-limit ends
    gamma: float = 0.99,
    clip_rho: float = 1.0,
    clip_c: float = 1.0,
):
    """V-trace targets (IMPALA). Returns (vs [T,B], pg_advantages [T,B]).

    vs_t = V(s_t) + sum_k gamma^k (prod c) rho_k delta_k  via reverse scan:
    vs_t = V_t + delta_t*rho_t + gamma*c_t*(vs_{t+1} - V_{t+1})

    Truncations are treated like terminations (zero bootstrap + cut the
    recurrence) — same bounded-bias choice as compute_gae; the stored next
    value at a boundary belongs to the next episode and must not leak in.
    """
    rhos = jnp.exp(target_logp - behaviour_logp)
    clipped_rhos = jnp.minimum(clip_rho, rhos)
    cs = jnp.minimum(clip_c, rhos)
    done = (
        terminateds.astype(bool)
        if truncateds is None
        else (terminateds.astype(bool) | truncateds.astype(bool))
    )
    nonterminal = 1.0 - done.astype(jnp.float32)
    next_values = jnp.concatenate([values[1:], final_values[None]], axis=0)
    deltas = clipped_rhos * (rewards + gamma * next_values * nonterminal - values)

    def scan_fn(acc, xs):
        delta, c, nt = xs
        acc = delta + gamma * nt * c * acc
        return acc, acc

    _, vs_minus_v = lax.scan(
        scan_fn,
        jnp.zeros_like(final_values),
        (deltas, cs, nonterminal),
        reverse=True,
    )
    vs = vs_minus_v + values
    next_vs = jnp.concatenate([vs[1:], final_values[None]], axis=0)
    pg_adv = clipped_rhos * (rewards + gamma * next_vs * nonterminal - values)
    return vs, pg_adv
