"""Algorithm: the trainable RL driver.

Reference analog: rllib/algorithms/algorithm.py:199 (Algorithm extends
Tune's Trainable; per-algo training_step; Checkpointable save/restore).
Same shape here: Algorithm IS a ray_tpu.tune Trainable, so
`Tuner(PPOConfig()...build_algo)` and plain `.train()` loops both work.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.rl.env_runner import EnvRunnerGroup, spec_from_env
from ray_tpu.rl.module import RLModuleSpec
from ray_tpu.tune.trainable import Trainable


class _EnvFactory:
    """Picklable `gym.make(id, **kwargs)` closure for remote env runners."""

    def __init__(self, env_id: str, kwargs: dict):
        self.env_id = env_id
        self.kwargs = kwargs

    def __call__(self):
        import gymnasium as gym

        return gym.make(self.env_id, **self.kwargs)


class Algorithm(Trainable):
    """Subclasses define `default_config()`, `build_components()`, and
    `training_step()`."""

    module_class: "type | None" = None  # override to swap the RLModule impl

    def __init__(self, config: "AlgorithmConfig | dict | None" = None):
        if isinstance(config, dict):
            cfg = self.default_config().update_from_dict(config)
        elif config is None:
            cfg = self.default_config()
        else:
            cfg = config
        self.config = cfg
        self.iteration = 0
        self._timesteps = 0
        self.setup(cfg)

    @classmethod
    def default_config(cls) -> AlgorithmConfig:
        return AlgorithmConfig(algo_class=cls)

    # -- Trainable contract -------------------------------------------------

    def setup(self, config) -> None:
        cfg = self.config
        if cfg.env is None:
            raise ValueError("config.environment(env=...) is required")
        env = cfg.env
        if isinstance(env, str) and cfg.env_config:
            env_id, env_kwargs = env, dict(cfg.env_config)
            env = _EnvFactory(env_id, env_kwargs)
        self._env_factory = env
        spec = spec_from_env(env)
        self.module_spec = RLModuleSpec(
            obs_dim=spec.obs_dim,
            action_dim=spec.action_dim,
            continuous=spec.continuous,
            hidden=tuple(cfg.model.get("hidden", (256, 256))),
            dueling=cfg.model.get("dueling", False),
            model_cls=self.module_class,
            action_high=spec.action_high,
        )
        self.env_runner_group = EnvRunnerGroup(
            env,
            self.module_spec,
            num_env_runners=cfg.num_env_runners,
            num_envs_per_runner=cfg.num_envs_per_env_runner,
            seed=cfg.seed,
            explore=cfg.explore,
        )
        self.build_components()

    def build_components(self) -> None:
        raise NotImplementedError

    def training_step(self) -> dict:
        raise NotImplementedError

    def step(self) -> dict:
        # train() is inherited from Trainable (same controller contract)
        metrics = self.training_step() or {}
        metrics.update(self.env_runner_group.metrics())
        metrics["num_env_steps_sampled_lifetime"] = self._timesteps
        return metrics

    def save_checkpoint(self) -> dict:
        return {
            "learner": self.learner_group.get_state(),
            "iteration": self.iteration,
            "timesteps": self._timesteps,
            "config": self.config.to_dict(),
        }

    def load_checkpoint(self, state: dict) -> None:
        self.learner_group.set_state(state["learner"])
        self.iteration = state["iteration"]
        self._timesteps = state["timesteps"]

    # reference names (Checkpointable mixin)
    def get_state(self) -> dict:
        return self.save_checkpoint()

    def set_state(self, state: dict) -> None:
        self.load_checkpoint(state)

    def cleanup(self) -> None:
        self.env_runner_group.stop()

    stop = cleanup

    # -- helpers shared by algorithms --------------------------------------

    @staticmethod
    def concat_rollouts(rollouts: list[dict]) -> dict:
        """Merge per-runner [T, B, ...] rollouts along the env axis."""
        out = {}
        for k in rollouts[0]:
            out[k] = (
                np.concatenate([r[k] for r in rollouts], axis=0)
                if k == "final_obs"
                else np.concatenate([r[k] for r in rollouts], axis=1)
            )
        return out
