"""EnvRunner: vectorized rollout collection actors.

Reference analog: rllib/env/single_agent_env_runner.py:66
(SingleAgentEnvRunner over gym vector envs) and env_runner_group.py:71
(EnvRunnerGroup of remote actors). TPU-first notes: the policy step is
one jitted `explore` program — obs batch in, actions/logp/vf out — so a
runner does exactly one device dispatch per env step regardless of
num_envs; rollouts are returned time-major [T, B, ...] numpy so the
learner can reshape/shard them straight onto the mesh batch axis.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import numpy as np

from ray_tpu.core import api
from ray_tpu.rl.connectors import ConnectorPipeline, default_env_to_module
from ray_tpu.rl.module import RLModuleSpec


def make_env(env: "str | Callable", num_envs: int, seed: int):
    import gymnasium as gym
    from gymnasium.vector import AutoresetMode

    # SAME_STEP autoreset: the step that reports done also returns the new
    # episode's first obs, so every stored transition is a real one (gymnasium
    # 1.x defaults to NEXT_STEP, which burns one garbage step per episode —
    # action ignored, reward 0 — and would poison rollouts and replay).
    if callable(env):
        return gym.vector.SyncVectorEnv(
            [lambda i=i: env() for i in range(num_envs)],
            autoreset_mode=AutoresetMode.SAME_STEP,
        )
    return gym.make_vec(
        env,
        num_envs=num_envs,
        vectorization_mode="sync",
        vector_kwargs={"autoreset_mode": AutoresetMode.SAME_STEP},
    )


def spec_from_env(env: "str | Callable") -> RLModuleSpec:
    """Derive obs/action dims by constructing one throwaway env instance."""
    import gymnasium as gym

    e = env() if callable(env) else gym.make(env)
    try:
        obs_dim = int(np.prod(e.observation_space.shape))
        if hasattr(e.action_space, "n"):
            return RLModuleSpec(obs_dim=obs_dim, action_dim=int(e.action_space.n))
        return RLModuleSpec(
            obs_dim=obs_dim,
            action_dim=int(np.prod(e.action_space.shape)),
            continuous=True,
            action_high=float(np.max(np.abs(e.action_space.high))),
        )
    finally:
        e.close()


class SingleAgentEnvRunner:
    """Collects rollouts from a vector env with the current policy weights.

    Used directly (local mode) or wrapped in an actor by EnvRunnerGroup.
    """

    def __init__(
        self,
        env: "str | Callable",
        module_spec: RLModuleSpec,
        *,
        num_envs: int = 8,
        seed: int = 0,
        explore: bool = True,
        connector: Optional[ConnectorPipeline] = None,
    ):
        self.envs = make_env(env, num_envs, seed)
        self.num_envs = num_envs
        self.module = module_spec.build()
        self.connector = connector or default_env_to_module()
        self.explore = explore
        self.key = jax.random.key(seed + 1)
        # One compiled program services every env step this runner takes.
        self._explore_fn = jax.jit(self.module.explore)
        self._infer_fn = jax.jit(self.module.inference)
        obs, _ = self.envs.reset(seed=seed)
        self.obs = self.connector({"obs": obs})["obs"]
        self._ep_ret = np.zeros(num_envs)
        self._ep_len = np.zeros(num_envs, np.int64)
        self._num_episodes = 0
        self._done_returns: list[float] = []
        self._done_lengths: list[int] = []

    def sample(self, params, rollout_len: int) -> dict:
        """Collect [T=rollout_len, B=num_envs] transitions, time-major."""
        T, B = rollout_len, self.num_envs
        cols = {
            "obs": np.empty((T, B) + self.obs.shape[1:], np.float32),
            "actions": None,
            "logp": np.empty((T, B), np.float32),
            "vf": np.empty((T, B), np.float32),
            "rewards": np.empty((T, B), np.float32),
            # terminated: true episode end (bootstrap 0); truncated: time limit
            "terminateds": np.empty((T, B), bool),
            "truncateds": np.empty((T, B), bool),
        }
        for t in range(T):
            self.key, k = jax.random.split(self.key)
            if self.explore:
                acts, logp, vf = self._explore_fn(params, self.obs, k)
            else:
                acts = self._infer_fn(params, self.obs)
                logp = vf = np.zeros((B,), np.float32)
            acts = np.asarray(acts)
            nxt, rew, term, trunc, _ = self.envs.step(acts)
            nxt = self.connector({"obs": nxt})["obs"]
            if cols["actions"] is None:
                cols["actions"] = np.empty((T,) + acts.shape, acts.dtype)
            cols["obs"][t] = self.obs
            cols["actions"][t] = acts
            cols["logp"][t] = np.asarray(logp)
            cols["vf"][t] = np.asarray(vf)
            cols["rewards"][t] = rew
            cols["terminateds"][t] = term
            cols["truncateds"][t] = trunc
            self._track_episodes(rew, term | trunc)
            self.obs = nxt
        cols["final_obs"] = self.obs.copy()  # bootstrap value at rollout end
        return cols

    def _track_episodes(self, rew, done):
        self._ep_ret += rew
        self._ep_len += 1
        for i in np.flatnonzero(done):
            self._num_episodes += 1
            self._done_returns.append(float(self._ep_ret[i]))
            self._done_lengths.append(int(self._ep_len[i]))
            self._ep_ret[i] = 0.0
            self._ep_len[i] = 0
        # bounded window (long runs finish millions of episodes)
        if len(self._done_returns) > 500:
            del self._done_returns[:-100]
            del self._done_lengths[:-100]

    def metrics(self) -> dict:
        """Windowed per-episode stats (reference: MetricsLogger episode returns).
        num_episodes is the lifetime count; means are over the last <=100."""
        rets, lens = self._done_returns[-100:], self._done_lengths[-100:]
        out = {
            "num_episodes": self._num_episodes,
            "episode_return_mean": float(np.mean(rets)) if rets else float("nan"),
            "episode_len_mean": float(np.mean(lens)) if lens else float("nan"),
        }
        return out

    def get_connector_state(self) -> dict:
        return self.connector.state()

    def set_connector_state(self, state: dict) -> bool:
        self.connector.set_state(state)
        return True

    def stop(self):
        self.envs.close()
        return True


class EnvRunnerGroup:
    """N env-runner actors + a sync/sample fan-out API (reference:
    rllib/env/env_runner_group.py:71)."""

    def __init__(
        self,
        env: "str | Callable",
        module_spec: RLModuleSpec,
        *,
        num_env_runners: int = 0,
        num_envs_per_runner: int = 8,
        seed: int = 0,
        explore: bool = True,
    ):
        self.num_env_runners = num_env_runners
        if num_env_runners == 0:
            self.local = SingleAgentEnvRunner(
                env, module_spec, num_envs=num_envs_per_runner, seed=seed,
                explore=explore,
            )
            self.remotes = []
        else:
            self.local = None
            runner_cls = api.remote(SingleAgentEnvRunner)
            self.remotes = [
                runner_cls.remote(
                    env,
                    module_spec,
                    num_envs=num_envs_per_runner,
                    seed=seed + 1000 * (i + 1),
                    explore=explore,
                )
                for i in range(num_env_runners)
            ]

    def sample(self, params, rollout_len: int) -> list[dict]:
        if self.local is not None:
            return [self.local.sample(params, rollout_len)]
        return api.get([r.sample.remote(params, rollout_len) for r in self.remotes])

    def sample_async(self, params, rollout_len: int):
        """Fire sample() on every remote runner, return refs (IMPALA path)."""
        if self.local is not None:
            return [api.put(self.local.sample(params, rollout_len))]
        return [r.sample.remote(params, rollout_len) for r in self.remotes]

    def metrics(self) -> dict:
        if self.local is not None:
            per = [self.local.metrics()]
        else:
            per = api.get([r.metrics.remote() for r in self.remotes])
        vals = [m["episode_return_mean"] for m in per if m["num_episodes"] > 0]
        lens = [m["episode_len_mean"] for m in per if m["num_episodes"] > 0]
        return {
            "episode_return_mean": float(np.mean(vals)) if vals else float("nan"),
            "episode_len_mean": float(np.mean(lens)) if lens else float("nan"),
            "num_episodes": sum(m["num_episodes"] for m in per),
        }

    def stop(self):
        if self.local is not None:
            self.local.stop()
        else:
            api.get([r.stop.remote() for r in self.remotes])
