"""Learner: the jitted SPMD update engine.

Reference analog: rllib/core/learner/learner.py (1,823 LoC; torch DDP
across learner actors) + learner_group.py:79. TPU-first redesign: where
the reference scales learners by running N actor processes with
torch DDP allreduce, here ONE pjit-compiled update program spans the
whole device mesh — data-parallel gradient psum is inserted by XLA from
the batch sharding, so "LearnerGroup" degenerates to mesh construction
plus this single program. Algorithms supply a pure
`loss_fn(params, batch, key) -> (loss, metrics)`.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax

from ray_tpu.parallel.mesh import MeshSpec, make_mesh
from ray_tpu.rl.module import RLModuleSpec

P = jax.sharding.PartitionSpec


class Learner:
    """Owns params + optimizer state; steps via one compiled update."""

    def __init__(
        self,
        module_spec: RLModuleSpec,
        loss_fn: Callable,
        *,
        optimizer: Optional[optax.GradientTransformation] = None,
        lr: float = 3e-4,
        grad_clip: float = 0.5,
        seed: int = 0,
        mesh: Optional[jax.sharding.Mesh] = None,
        batch_axis: "Callable[[str, jax.Array], int] | None" = None,
    ):
        self.module = module_spec.build()
        self.loss_fn = loss_fn
        # Which axis of each batch leaf is the data-parallel axis (default 0).
        # Time-major algorithms (IMPALA) shard axis 1 so scans over T stay local.
        self.batch_axis = batch_axis or (lambda name, leaf: 0)
        self.optimizer = optimizer or optax.chain(
            optax.clip_by_global_norm(grad_clip), optax.adam(lr)
        )
        self.params = self.module.init(jax.random.key(seed))
        self.opt_state = self.optimizer.init(self.params)
        self.key = jax.random.key(seed + 17)
        self.mesh = mesh
        self._step = self._compile()
        self.steps = 0

    def _compile(self):
        def update(params, opt_state, batch, key):
            (loss, metrics), grads = jax.value_and_grad(
                self.loss_fn, has_aux=True
            )(params, batch, key)
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            metrics = dict(metrics, total_loss=loss, grad_norm=optax.global_norm(grads))
            return params, opt_state, metrics

        if self.mesh is None:
            return jax.jit(update, donate_argnums=(0, 1))
        # SPMD: replicate params, shard each batch leaf's data axis over dp;
        # XLA inserts the gradient psum (the reference's DDP allreduce).
        repl = jax.sharding.NamedSharding(self.mesh, P())
        return jax.jit(update, donate_argnums=(0, 1), out_shardings=(repl, repl, repl))

    def _shard_batch(self, batch: dict) -> dict:
        if self.mesh is None:
            return {k: jnp.asarray(v) for k, v in batch.items()}
        out = {}
        for name, leaf in batch.items():
            leaf = jnp.asarray(leaf)
            ax = self.batch_axis(name, leaf)
            spec = [None] * leaf.ndim
            if leaf.ndim and leaf.shape[ax] % self.mesh.shape["dp"] == 0:
                spec[ax] = "dp"
            out[name] = jax.device_put(
                leaf, jax.sharding.NamedSharding(self.mesh, P(*spec))
            )
        return out

    def update(self, batch: dict) -> dict:
        """One gradient step on a batch; returns host metrics."""
        self.key, k = jax.random.split(self.key)
        batch = self._shard_batch(batch)
        self.params, self.opt_state, metrics = self._step(
            self.params, self.opt_state, batch, k
        )
        self.steps += 1
        return {k2: float(v) for k2, v in metrics.items()}

    def get_state(self) -> dict:
        return {
            "params": jax.device_get(self.params),
            "opt_state": jax.device_get(self.opt_state),
            "steps": self.steps,
        }

    def set_state(self, state: dict) -> None:
        self.params = jax.device_put(state["params"])
        self.opt_state = jax.device_put(state["opt_state"])
        self.steps = state["steps"]


class LearnerGroup:
    """Scaling wrapper: builds the mesh and the one SPMD learner on it.

    The reference's LearnerGroup manages N DDP learner actors; on TPU
    the mesh IS the group (see module docstring), so this class handles
    mesh selection + future multi-host bootstrap, keeping the
    reference's API seam for algorithms.
    """

    def __init__(
        self,
        module_spec: RLModuleSpec,
        loss_fn: Callable,
        *,
        num_learners: int = 0,
        optimizer: Optional[optax.GradientTransformation] = None,
        lr: float = 3e-4,
        grad_clip: float = 0.5,
        seed: int = 0,
        batch_axis: "Callable[[str, jax.Array], int] | None" = None,
    ):
        mesh = None
        if num_learners > 1:
            mesh = make_mesh(MeshSpec(dp=num_learners))
        self.learner = Learner(
            module_spec,
            loss_fn,
            optimizer=optimizer,
            lr=lr,
            grad_clip=grad_clip,
            seed=seed,
            mesh=mesh,
            batch_axis=batch_axis,
        )

    def update(self, batch: dict) -> dict:
        return self.learner.update(batch)

    @property
    def params(self):
        return self.learner.params

    def get_state(self) -> dict:
        return self.learner.get_state()

    def set_state(self, state: dict) -> None:
        self.learner.set_state(state)
