"""RLModule: the neural-net abstraction for RL algorithms.

Reference analog: rllib/core/rl_module/rl_module.py (812 LoC, torch).
TPU-first redesign: a module is a *functional spec* — `init(key)` builds
a params pytree, and `forward_*` are pure jittable functions — so env
runners, learners, and target networks all share one set of weights as
a pytree that can be donated, sharded with pjit, or shipped across
hosts without framework object baggage.

Forward has the reference's three entry points (rl_module.py
forward_inference / forward_exploration / forward_train) collapsed into
`forward` (deterministic heads) + distribution helpers; algorithms pick
sampling vs. mode at their call site inside jit.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Sequence

import jax
import jax.numpy as jnp

from ray_tpu.nn.layers import init_dense
from ray_tpu.rl.distributions import get_distribution


@dataclass(frozen=True)
class RLModuleSpec:
    """Static description of a module; `build()` yields the functional module."""

    obs_dim: int
    action_dim: int  # num discrete actions, or continuous action size
    continuous: bool = False
    hidden: Sequence[int] = (256, 256)
    dueling: bool = False  # DQN-style value/advantage split of the Q head
    model_cls: "type[RLModule] | None" = None
    # Box bounds for continuous spaces (SAC's tanh squash scales to these)
    action_high: float = 1.0

    def build(self) -> "RLModule":
        cls = self.model_cls or MLPModule
        return cls(self)


class RLModule:
    """Functional policy+value module. Subclass to swap architectures."""

    def __init__(self, spec: RLModuleSpec):
        self.spec = spec
        self.dist = get_distribution(
            "diag_gaussian" if spec.continuous else "categorical"
        )
        # Output head width: logits for discrete, mean|logstd for continuous.
        self.out_dim = spec.action_dim * (2 if spec.continuous else 1)

    # -- override points ----------------------------------------------------

    def init(self, key: jax.Array):
        raise NotImplementedError

    def forward(self, params, obs: jax.Array) -> dict:
        """Returns {"action_dist_inputs": [..., out_dim], "vf": [...]}"""
        raise NotImplementedError

    # -- shared jittable helpers --------------------------------------------

    def explore(self, params, obs, key):
        """Sample actions + logp for rollout collection (one jit program)."""
        out = self.forward(params, obs)
        acts = self.dist.sample(key, out["action_dist_inputs"])
        logp = self.dist.logp(out["action_dist_inputs"], acts)
        return acts, logp, out["vf"]

    def inference(self, params, obs):
        out = self.forward(params, obs)
        return self.dist.mode(out["action_dist_inputs"])


def _mlp_init(key, dims: Sequence[int], dtype=jnp.float32):
    layers = []
    for i, (d_in, d_out) in enumerate(zip(dims[:-1], dims[1:])):
        key, k = jax.random.split(key)
        layers.append(
            {
                "w": init_dense(k, (d_in, d_out), dtype),
                "b": jnp.zeros((d_out,), dtype),
            }
        )
    return layers


def _mlp_apply(layers, x, final_activation=False):
    for i, lyr in enumerate(layers):
        x = x @ lyr["w"] + lyr["b"]
        if final_activation or i < len(layers) - 1:
            x = jax.nn.tanh(x)
    return x


class MLPModule(RLModule):
    """Default fully-connected torso with separate policy / value heads.

    Mirrors rllib's default MLP encoder+heads catalog output; the value
    head is always present (costs one extra column of matmul on the MXU,
    avoids a second spec for value-free algorithms).
    """

    def init(self, key: jax.Array):
        s = self.spec
        k_pi, k_vf = jax.random.split(key)
        pi_dims = [s.obs_dim, *s.hidden, self.out_dim]
        vf_dims = [s.obs_dim, *s.hidden, 1]
        params = {
            "pi": _mlp_init(k_pi, pi_dims),
            "vf": _mlp_init(k_vf, vf_dims),
        }
        if s.dueling:
            key, k_adv = jax.random.split(key)
            params["adv"] = _mlp_init(k_adv, pi_dims)
        return params

    def forward(self, params, obs: jax.Array) -> dict:
        out = _mlp_apply(params["pi"], obs)
        if self.spec.dueling:
            # Q(s,a) = V(s) + A(s,a) - mean_a A(s,a)
            adv = _mlp_apply(params["adv"], obs)
            v = _mlp_apply(params["vf"], obs)
            out = v + adv - jnp.mean(adv, axis=-1, keepdims=True)
            return {"action_dist_inputs": out, "vf": v[..., 0]}
        vf = _mlp_apply(params["vf"], obs)[..., 0]
        return {"action_dist_inputs": out, "vf": vf}
