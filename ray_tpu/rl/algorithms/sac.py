"""SAC: soft actor-critic for continuous control.

Reference analog: rllib/algorithms/sac/ (twin delayed Q critics, tanh-
squashed Gaussian actor, automatic entropy temperature). The whole
update — both critic losses, the reparameterized actor loss, the alpha
loss, and the polyak target sync — is ONE jitted program per train
batch; replay stays host-side numpy (replay.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rl.algorithm import Algorithm
from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.rl.module import MLPModule, RLModule, _mlp_apply, _mlp_init
from ray_tpu.rl.replay import ReplayBuffer

LOG_STD_MIN, LOG_STD_MAX = -20.0, 2.0


class SACModule(RLModule):
    """Squashed-Gaussian actor + twin Q critics in one param tree."""

    def init(self, key: jax.Array):
        s = self.spec
        k_pi, k_q1, k_q2 = jax.random.split(key, 3)
        return {
            "pi": _mlp_init(k_pi, [s.obs_dim, *s.hidden, 2 * s.action_dim]),
            "q1": _mlp_init(k_q1, [s.obs_dim + s.action_dim, *s.hidden, 1]),
            "q2": _mlp_init(k_q2, [s.obs_dim + s.action_dim, *s.hidden, 1]),
        }

    def actor_out(self, params, obs):
        out = _mlp_apply(params["pi"], obs)
        mean, log_std = jnp.split(out, 2, axis=-1)
        return mean, jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)

    def sample_action(self, params, obs, key):
        """Reparameterized tanh-squashed sample -> (action, logp)."""
        mean, log_std = self.actor_out(params, obs)
        std = jnp.exp(log_std)
        raw = mean + std * jax.random.normal(key, mean.shape)
        act = jnp.tanh(raw)
        # tanh change-of-variables correction, numerically stable form
        logp = (
            -0.5 * (((raw - mean) / std) ** 2 + 2 * log_std + jnp.log(2 * jnp.pi))
        ).sum(-1)
        logp -= (2.0 * (jnp.log(2.0) - raw - jax.nn.softplus(-2.0 * raw))).sum(-1)
        return act * self.spec.action_high, logp

    def q_values(self, params, obs, act):
        """Both critics' Q(s, a) (act in env scale)."""
        x = jnp.concatenate([obs, act / self.spec.action_high], axis=-1)
        return (
            _mlp_apply(params["q1"], x)[..., 0],
            _mlp_apply(params["q2"], x)[..., 0],
        )

    # rollout-collection surface used by the env runner
    def explore(self, params, obs, key):
        act, logp = self.sample_action(params, obs, key)
        return act, logp, jnp.zeros(act.shape[:-1], jnp.float32)

    def inference(self, params, obs):
        mean, _ = self.actor_out(params, obs)
        return jnp.tanh(mean) * self.spec.action_high

    def forward(self, params, obs):
        mean, log_std = self.actor_out(params, obs)
        return {
            "action_dist_inputs": jnp.concatenate([mean, log_std], -1),
            "vf": jnp.zeros(obs.shape[:-1], jnp.float32),
        }


class SACConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=SAC)
        self.lr = 3e-4
        self.actor_lr = None        # default: lr
        self.alpha_lr = None        # default: lr
        self.tau = 0.005            # polyak target-critic rate
        self.replay_capacity = 100_000
        self.learning_starts = 1000
        self.train_batch_size = 256
        self.rollout_fragment_length = 4
        self.train_intensity = 1    # learner steps per sampling round
        self.target_entropy = None  # default: -action_dim
        self.initial_alpha = 1.0
        # offline / conservative (CQL) extensions
        self.cql_alpha = 0.0        # >0 adds the conservative penalty
        self.cql_n_actions = 4      # random actions for the logsumexp

    def training(self, **kwargs):
        for k in (
            "actor_lr", "alpha_lr", "tau", "replay_capacity", "learning_starts",
            "train_intensity", "target_entropy", "initial_alpha",
            "cql_alpha", "cql_n_actions",
        ):
            if k in kwargs:
                setattr(self, k, kwargs.pop(k))
        return super().training(**kwargs)


class SAC(Algorithm):
    module_class = SACModule

    @classmethod
    def default_config(cls) -> SACConfig:
        return SACConfig()

    def setup(self, config) -> None:
        self.config.model = dict(self.config.model)
        super().setup(config)

    def build_components(self) -> None:
        cfg = self.config
        if not self.module_spec.continuous:
            raise ValueError("SAC requires a continuous (Box) action space")
        # module_class = SACModule (class attr) already routed through setup
        module = self.module_spec.build()
        self.module = module
        self.params = module.init(jax.random.key(cfg.seed))
        self.target_q = {
            "q1": jax.tree.map(jnp.copy, self.params["q1"]),
            "q2": jax.tree.map(jnp.copy, self.params["q2"]),
        }
        self.log_alpha = jnp.log(jnp.float32(cfg.initial_alpha))
        self.critic_opt = optax.adam(cfg.lr)
        self.actor_opt = optax.adam(cfg.actor_lr or cfg.lr)
        self.alpha_opt = optax.adam(cfg.alpha_lr or cfg.lr)
        self.critic_state = self.critic_opt.init(
            {"q1": self.params["q1"], "q2": self.params["q2"]}
        )
        self.actor_state = self.actor_opt.init(self.params["pi"])
        self.alpha_state = self.alpha_opt.init(self.log_alpha)
        self.replay = ReplayBuffer(cfg.replay_capacity, seed=cfg.seed)
        self.key = jax.random.key(cfg.seed + 29)
        self._learn_steps = 0
        self._build_update()
        self.learner_group = _SACLearnerShim(self)

    def _build_update(self):
        cfg = self.config
        module: SACModule = self.module
        gamma, tau = cfg.gamma, cfg.tau
        target_entropy = (
            cfg.target_entropy
            if cfg.target_entropy is not None
            else -float(self.module_spec.action_dim)
        )
        cql_alpha, cql_n = cfg.cql_alpha, cfg.cql_n_actions
        high = self.module_spec.action_high

        @jax.jit
        def update(params, target_q, log_alpha, critic_state, actor_state,
                   alpha_state, batch, key):
            k_next, k_pi, k_cql = jax.random.split(key, 3)
            alpha = jnp.exp(log_alpha)

            # -- critics ----------------------------------------------------
            next_act, next_logp = module.sample_action(
                params, batch["next_obs"], k_next
            )
            tq1, tq2 = module.q_values(
                {**params, "q1": target_q["q1"], "q2": target_q["q2"]},
                batch["next_obs"], next_act,
            )
            target = batch["rewards"] + gamma * (1.0 - batch["terminateds"]) * (
                jnp.minimum(tq1, tq2) - alpha * next_logp
            )
            target = jax.lax.stop_gradient(target)

            def critic_loss(qp):
                q1, q2 = module.q_values(
                    {**params, "q1": qp["q1"], "q2": qp["q2"]},
                    batch["obs"], batch["actions"],
                )
                loss = ((q1 - target) ** 2 + (q2 - target) ** 2).mean()
                if cql_alpha > 0.0:
                    # conservative penalty: push down Q on out-of-dataset
                    # actions (random + policy), up on dataset actions
                    B = batch["obs"].shape[0]
                    rand = jax.random.uniform(
                        k_cql, (cql_n, B, module.spec.action_dim),
                        minval=-high, maxval=high,
                    )
                    pi_a, _ = module.sample_action(params, batch["obs"], k_cql)
                    cat = jnp.concatenate([rand, pi_a[None]], 0)  # [N+1, B, A]

                    def q_of(a):
                        return module.q_values(
                            {**params, "q1": qp["q1"], "q2": qp["q2"]},
                            batch["obs"], a,
                        )

                    q1_all, q2_all = jax.vmap(q_of)(cat)  # [N+1, B]
                    penalty = (
                        (jax.scipy.special.logsumexp(q1_all, axis=0) - q1).mean()
                        + (jax.scipy.special.logsumexp(q2_all, axis=0) - q2).mean()
                    )
                    loss = loss + cql_alpha * penalty
                return loss, (q1.mean(), q2.mean())

            qp = {"q1": params["q1"], "q2": params["q2"]}
            (closs, (q1m, q2m)), cgrads = jax.value_and_grad(
                critic_loss, has_aux=True
            )(qp)
            cupd, critic_state = self.critic_opt.update(cgrads, critic_state, qp)
            qp = optax.apply_updates(qp, cupd)
            params = {**params, "q1": qp["q1"], "q2": qp["q2"]}

            # -- actor ------------------------------------------------------
            def actor_loss(pi):
                act, logp = module.sample_action(
                    {**params, "pi": pi}, batch["obs"], k_pi
                )
                q1, q2 = module.q_values(params, batch["obs"], act)
                return (alpha * logp - jnp.minimum(q1, q2)).mean(), logp.mean()

            (aloss, logp_mean), agrads = jax.value_and_grad(
                actor_loss, has_aux=True
            )(params["pi"])
            aupd, actor_state = self.actor_opt.update(
                agrads, actor_state, params["pi"]
            )
            params = {**params, "pi": optax.apply_updates(params["pi"], aupd)}

            # -- temperature ------------------------------------------------
            def alpha_loss(la):
                return -(jnp.exp(la) * (logp_mean + target_entropy))

            lgrad = jax.grad(alpha_loss)(log_alpha)
            lupd, alpha_state = self.alpha_opt.update(lgrad, alpha_state, log_alpha)
            log_alpha = optax.apply_updates(log_alpha, lupd)

            # -- polyak target sync -----------------------------------------
            target_q = jax.tree.map(
                lambda t, o: (1 - tau) * t + tau * o,
                target_q, {"q1": params["q1"], "q2": params["q2"]},
            )
            metrics = {
                "critic_loss": closs, "actor_loss": aloss,
                "alpha": jnp.exp(log_alpha), "q1_mean": q1m, "q2_mean": q2m,
                "entropy": -logp_mean,
            }
            return (params, target_q, log_alpha, critic_state, actor_state,
                    alpha_state, metrics)

        self._update = update

    def training_step(self) -> dict:
        cfg = self.config
        rollouts = self.env_runner_group.sample(
            self.params, cfg.rollout_fragment_length
        )
        batch = self.concat_rollouts(rollouts)
        self._add_transitions(batch)
        metrics: dict = {"replay_size": len(self.replay)}
        if len(self.replay) < cfg.learning_starts:
            return metrics
        for _ in range(cfg.train_intensity):
            mb = self.replay.sample(cfg.train_batch_size)
            dev = {k: jnp.asarray(v) for k, v in mb.items()}
            self.key, k = jax.random.split(self.key)
            (self.params, self.target_q, self.log_alpha, self.critic_state,
             self.actor_state, self.alpha_state, m) = self._update(
                self.params, self.target_q, self.log_alpha, self.critic_state,
                self.actor_state, self.alpha_state, dev, k,
            )
            self._learn_steps += 1
        metrics.update({k: float(v) for k, v in m.items()})
        metrics["learn_steps"] = self._learn_steps
        return metrics

    def _add_transitions(self, batch: dict) -> None:
        T, B = batch["rewards"].shape
        self._timesteps += T * B
        obs_seq = np.concatenate([batch["obs"], batch["final_obs"][None]], axis=0)
        flat = {
            "obs": batch["obs"].reshape(T * B, -1),
            "actions": batch["actions"].reshape(T * B, -1),
            "rewards": batch["rewards"].reshape(T * B),
            "next_obs": obs_seq[1:].reshape(T * B, -1),
            "terminateds": batch["terminateds"].reshape(T * B).astype(np.float32),
        }
        self.replay.add_batch(flat)

    def offline_update(self, dataset_batch: dict) -> dict:
        """One gradient step straight from an offline batch (the CQL path:
        reference rllib/algorithms/cql trains SAC+penalty from OfflineData
        with no env interaction)."""
        dev = {k: jnp.asarray(v) for k, v in dataset_batch.items()}
        self.key, k = jax.random.split(self.key)
        (self.params, self.target_q, self.log_alpha, self.critic_state,
         self.actor_state, self.alpha_state, m) = self._update(
            self.params, self.target_q, self.log_alpha, self.critic_state,
            self.actor_state, self.alpha_state, dev, k,
        )
        self._learn_steps += 1
        return {k: float(v) for k, v in m.items()}


class _SACLearnerShim:
    def __init__(self, algo: "SAC"):
        self.algo = algo

    def get_state(self) -> dict:
        a = self.algo
        return {
            "params": jax.device_get(a.params),
            "target_q": jax.device_get(a.target_q),
            "log_alpha": jax.device_get(a.log_alpha),
            "steps": a._learn_steps,
        }

    def set_state(self, state: dict) -> None:
        a = self.algo
        a.params = jax.device_put(state["params"])
        a.target_q = jax.device_put(state["target_q"])
        a.log_alpha = jax.device_put(state["log_alpha"])
        a._learn_steps = state["steps"]
