"""DQN: deep Q-learning with target network + optional double/dueling/PER.

Reference analog: rllib/algorithms/dqn/ (DQN rainbow-lite: double-Q,
dueling heads, prioritized replay, n-step). The Q update (gather →
target max → Huber → adam → periodic target sync via lax.cond on the
step counter) is one jitted program; replay stays in host numpy
(replay.py) and ships one contiguous batch per step to the device.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rl.algorithm import Algorithm
from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.rl.replay import PrioritizedReplayBuffer, ReplayBuffer
from ray_tpu.rl.module import MLPModule, RLModuleSpec


class DQNModule(MLPModule):
    """Q-network: epsilon-greedy exploration driven by an `_epsilon` leaf
    the algorithm injects into the sampling params each round."""

    def explore(self, params, obs, key):
        out = self.forward(params, obs)
        q = out["action_dist_inputs"]
        greedy = jnp.argmax(q, axis=-1)
        eps = params["_epsilon"] if "_epsilon" in params else jnp.float32(0.0)
        k_act, k_mask = jax.random.split(key)
        rand = jax.random.randint(k_act, greedy.shape, 0, q.shape[-1])
        acts = jnp.where(jax.random.uniform(k_mask, greedy.shape) < eps, rand, greedy)
        return acts, jnp.zeros(greedy.shape, jnp.float32), out["vf"]

    def forward(self, params, obs):
        # drop the exploration leaf before the net sees the tree
        return super().forward(
            {k: v for k, v in params.items() if k != "_epsilon"}, obs
        )


class DQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=DQN)
        self.lr = 5e-4
        self.replay_capacity = 50_000
        self.learning_starts = 1000
        self.target_update_freq = 500  # in learner steps
        self.double_q = True
        self.dueling = False
        self.prioritized_replay = False
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.05
        self.epsilon_timesteps = 10_000
        self.n_step = 1
        self.train_batch_size = 64
        self.rollout_fragment_length = 4
        self.train_intensity = 1  # learner steps per sampling round

    def training(self, **kwargs):
        for k in (
            "replay_capacity", "learning_starts", "target_update_freq", "double_q",
            "dueling", "prioritized_replay", "epsilon_initial", "epsilon_final",
            "epsilon_timesteps", "n_step", "train_intensity",
        ):
            if k in kwargs:
                setattr(self, k, kwargs.pop(k))
        return super().training(**kwargs)


class DQN(Algorithm):
    module_class = DQNModule

    @classmethod
    def default_config(cls) -> DQNConfig:
        return DQNConfig()

    def setup(self, config) -> None:
        cfg = self.config
        cfg.model = dict(cfg.model, dueling=cfg.dueling)
        super().setup(config)

    def build_components(self) -> None:
        cfg = self.config
        if self.module_spec.continuous:
            raise ValueError("DQN requires a discrete action space")
        module = self.module_spec.build()
        self.module = module
        self.optimizer = optax.adam(cfg.lr)
        self.params = module.init(jax.random.key(cfg.seed))
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self.opt_state = self.optimizer.init(self.params)
        self.key = jax.random.key(cfg.seed + 17)
        if cfg.prioritized_replay:
            self.replay = PrioritizedReplayBuffer(cfg.replay_capacity, seed=cfg.seed)
        else:
            self.replay = ReplayBuffer(cfg.replay_capacity, seed=cfg.seed)
        self._learn_steps = 0
        self._build_update()
        self.learner_group = _DQNLearnerShim(self)

    def _build_update(self):
        cfg = self.config
        gamma_n = cfg.gamma**cfg.n_step
        double_q = cfg.double_q
        module = self.module
        sync_every = cfg.target_update_freq

        def q_of(params, obs):
            return module.forward(params, obs)["action_dist_inputs"]

        @jax.jit
        def update(params, target_params, opt_state, batch, step):
            def loss_fn(p):
                q = q_of(p, batch["obs"])
                q_sa = jnp.take_along_axis(q, batch["actions"][:, None], 1)[:, 0]
                q_next_t = q_of(target_params, batch["next_obs"])
                if double_q:
                    # argmax under online net, value under target net
                    best = jnp.argmax(q_of(p, batch["next_obs"]), axis=1)
                    q_next = jnp.take_along_axis(q_next_t, best[:, None], 1)[:, 0]
                else:
                    q_next = q_next_t.max(axis=1)
                target = batch["rewards"] + gamma_n * q_next * (1.0 - batch["terminateds"])
                td = q_sa - jax.lax.stop_gradient(target)
                huber = optax.huber_loss(td, delta=1.0)
                w = batch.get("weights", jnp.ones_like(td))
                return (w * huber).mean(), td

            (loss, td), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            target_params = jax.lax.cond(
                (step + 1) % sync_every == 0,
                lambda: jax.tree.map(jnp.copy, params),
                lambda: target_params,
            )
            return params, target_params, opt_state, loss, td

        self._update = update
        self._q_fn = jax.jit(q_of)

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self._timesteps / max(1, cfg.epsilon_timesteps))
        return cfg.epsilon_initial + frac * (cfg.epsilon_final - cfg.epsilon_initial)

    def training_step(self) -> dict:
        cfg = self.config
        # ---- sample: epsilon-greedy; epsilon rides along in the params tree ----
        sample_params = dict(self.params, _epsilon=jnp.float32(self._epsilon()))
        rollouts = self.env_runner_group.sample(sample_params, cfg.rollout_fragment_length)
        batch = self.concat_rollouts(rollouts)
        self._add_transitions(batch)
        metrics = {"epsilon": self._epsilon(), "replay_size": len(self.replay)}
        if len(self.replay) < cfg.learning_starts:
            return metrics
        # ---- learn ----
        for _ in range(cfg.train_intensity):
            if cfg.prioritized_replay:
                mb = self.replay.sample(cfg.train_batch_size)
                idx = mb.pop("idx")
            else:
                mb = self.replay.sample(cfg.train_batch_size)
                idx = None
            dev = {k: jnp.asarray(v) for k, v in mb.items()}
            self.params, self.target_params, self.opt_state, loss, td = self._update(
                self.params, self.target_params, self.opt_state, dev, self._learn_steps
            )
            self._learn_steps += 1
            if idx is not None:
                self.replay.update_priorities(idx, np.asarray(td))
            metrics["loss"] = float(loss)
        metrics["learn_steps"] = self._learn_steps
        return metrics

    def _add_transitions(self, batch: dict) -> None:
        """Flatten [T, B] rollouts to n-step transitions in the replay buffer."""
        cfg = self.config
        T, B = batch["rewards"].shape
        n = cfg.n_step
        obs_seq = np.concatenate([batch["obs"], batch["final_obs"][None]], axis=0)
        self._timesteps += T * B
        rows = []
        for t in range(T - n + 1):
            rew = np.zeros(B, np.float32)
            done = np.zeros(B, bool)
            for k in range(n):
                rew += (cfg.gamma**k) * batch["rewards"][t + k] * ~done
                done |= batch["terminateds"][t + k] | batch["truncateds"][t + k]
            rows.append(
                {
                    "obs": batch["obs"][t].reshape(B, -1),
                    "actions": batch["actions"][t],
                    "rewards": rew,
                    "next_obs": obs_seq[t + n].reshape(B, -1),
                    # Any episode boundary inside the n-step window kills the
                    # bootstrap: next_obs at t+n belongs to a later episode
                    # then (autoreset), so bootstrapping through it would leak
                    # cross-episode values into the TD target.
                    "terminateds": done.astype(np.float32),
                }
            )
        flat = {k: np.concatenate([r[k] for r in rows]) for k in rows[0]}
        self.replay.add_batch(flat)


class _DQNLearnerShim:
    def __init__(self, algo: DQN):
        self.algo = algo

    def get_state(self) -> dict:
        a = self.algo
        return {
            "params": jax.device_get(a.params),
            "target_params": jax.device_get(a.target_params),
            "opt_state": jax.device_get(a.opt_state),
            "steps": a._learn_steps,
        }

    def set_state(self, state: dict) -> None:
        a = self.algo
        a.params = jax.device_put(state["params"])
        a.target_params = jax.device_put(state["target_params"])
        a.opt_state = jax.device_put(state["opt_state"])
        a._learn_steps = state["steps"]
