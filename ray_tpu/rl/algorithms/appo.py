"""APPO: asynchronous PPO on the IMPALA actor-learner topology.

Reference analog: rllib/algorithms/appo/appo.py:1 (+ appo_learner /
default_appo_rl_module) — PPO's clipped surrogate objective applied to
ASYNCHRONOUSLY collected, slightly-stale rollouts, with V-trace
correcting the off-policy gap in both the value targets and the
policy-gradient advantages. Differences from the reference kept
deliberate: the target-network smoothing of value bootstraps is
replaced by stop-gradient V-trace targets from the live params (the
reference's own "new API stack" APPO moved the same way), and the
optional KL penalty against the behavior policy is a config switch.
"""

from __future__ import annotations

import jax.numpy as jnp

from ray_tpu.rl.algorithms.impala import IMPALA, IMPALAConfig


class APPOConfig(IMPALAConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = APPO
        self.clip_param = 0.3
        self.use_kl_loss = False
        self.kl_coeff = 0.2

    def training(self, **kwargs):
        for k in ("clip_param", "use_kl_loss", "kl_coeff"):
            if k in kwargs:
                setattr(self, k, kwargs.pop(k))
        return super().training(**kwargs)


class APPO(IMPALA):
    """Only the LOSS differs from IMPALA — topology, learner wiring, and
    V-trace targets come from the base class (_make_loss hook)."""

    @classmethod
    def default_config(cls) -> APPOConfig:
        return APPOConfig()

    def _make_loss(self, module):
        cfg = self.config
        vf_coeff, ent_coeff = cfg.vf_loss_coeff, cfg.entropy_coeff
        clip = cfg.clip_param
        use_kl, kl_coeff = cfg.use_kl_loss, cfg.kl_coeff

        def loss_fn(params, batch, _key):
            out = module.forward(params, batch["obs"])  # [T, B, ...]
            target_logp = module.dist.logp(
                out["action_dist_inputs"], batch["actions"]
            )
            vs, pg_adv = self._vtrace_targets(
                module, params, batch, out, target_logp
            )
            adv = (pg_adv - pg_adv.mean()) / (pg_adv.std() + 1e-8)
            # PPO clipped surrogate against the BEHAVIOR policy's logp —
            # the asynchronous staleness IS the "old policy" gap
            ratio = jnp.exp(target_logp - batch["logp"])
            surr = jnp.minimum(
                ratio * adv, jnp.clip(ratio, 1 - clip, 1 + clip) * adv
            )
            pg_loss = -surr.mean()
            vf_loss = 0.5 * jnp.square(out["vf"] - vs).mean()
            entropy = module.dist.entropy(out["action_dist_inputs"]).mean()
            loss = pg_loss + vf_coeff * vf_loss - ent_coeff * entropy
            metrics = {
                "policy_loss": pg_loss, "vf_loss": vf_loss, "entropy": entropy,
                "mean_ratio": ratio.mean(),
            }
            if use_kl:
                # sample KL(behavior || target) estimate from logp gap
                kl = (batch["logp"] - target_logp).mean()
                loss = loss + kl_coeff * jnp.abs(kl)
                metrics["kl"] = kl
            return loss, metrics

        return loss_fn
