from ray_tpu.rl.algorithms.appo import APPO, APPOConfig
from ray_tpu.rl.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rl.algorithms.impala import IMPALA, IMPALAConfig
from ray_tpu.rl.algorithms.ppo import PPO, PPOConfig
from ray_tpu.rl.algorithms.sac import SAC, SACConfig

__all__ = [
    "APPO", "APPOConfig", "PPO", "PPOConfig", "IMPALA", "IMPALAConfig",
    "DQN", "DQNConfig", "SAC", "SACConfig",
]
