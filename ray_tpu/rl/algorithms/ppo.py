"""PPO: clipped-surrogate policy optimization.

Reference analog: rllib/algorithms/ppo/ppo.py:388 (training_step:
sample → GAE connector → minibatch-epochs learner update). TPU-first
shape: GAE runs as a jitted scan (postprocessing.py); the epoch/
minibatch sweep is ONE compiled program — `lax.scan` over shuffled
minibatch slices inside jit — so a whole PPO update is a single device
dispatch instead of epochs×minibatches separate steps.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rl.algorithm import Algorithm
from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.rl.postprocessing import compute_gae
from ray_tpu.rl.module import RLModuleSpec


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=PPO)
        self.lam = 0.95
        self.clip_param = 0.2
        self.vf_clip_param = 10.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.0
        self.rollout_fragment_length = 64

    def training(self, **kwargs):
        for k in ("lam", "clip_param", "vf_clip_param", "vf_loss_coeff", "entropy_coeff"):
            if k in kwargs:
                setattr(self, k, kwargs.pop(k))
        return super().training(**kwargs)


class PPO(Algorithm):
    @classmethod
    def default_config(cls) -> PPOConfig:
        return PPOConfig()

    def build_components(self) -> None:
        cfg = self.config
        module = self.module_spec.build()
        self.module = module
        self._value_fn = jax.jit(lambda p, o: module.forward(p, o)["vf"])

        clip, vf_clip = cfg.clip_param, cfg.vf_clip_param
        vf_coeff, ent_coeff = cfg.vf_loss_coeff, cfg.entropy_coeff

        def loss_fn(params, mb, _key):
            out = module.forward(params, mb["obs"])
            logp = module.dist.logp(out["action_dist_inputs"], mb["actions"])
            ratio = jnp.exp(logp - mb["logp"])
            adv = mb["advantages"]
            adv = (adv - adv.mean()) / (adv.std() + 1e-8)
            surr = jnp.minimum(
                ratio * adv, jnp.clip(ratio, 1 - clip, 1 + clip) * adv
            )
            # clipped value loss (reference ppo_torch_learner vf clipping)
            vf = out["vf"]
            vf_err = jnp.square(vf - mb["value_targets"])
            vf_clipped = mb["vf_old"] + jnp.clip(vf - mb["vf_old"], -vf_clip, vf_clip)
            vf_err = jnp.maximum(vf_err, jnp.square(vf_clipped - mb["value_targets"]))
            entropy = module.dist.entropy(out["action_dist_inputs"])
            loss = (
                -surr.mean() + vf_coeff * 0.5 * vf_err.mean() - ent_coeff * entropy.mean()
            )
            return loss, {
                "policy_loss": -surr.mean(),
                "vf_loss": vf_err.mean(),
                "entropy": entropy.mean(),
                "kl": (mb["logp"] - logp).mean(),
            }

        self.optimizer = optax.chain(
            optax.clip_by_global_norm(cfg.grad_clip), optax.adam(cfg.lr)
        )
        self.params = module.init(jax.random.key(cfg.seed))
        self.opt_state = self.optimizer.init(self.params)
        self.key = jax.random.key(cfg.seed + 17)
        self._update = self._compile_update(loss_fn)
        # the Algorithm checkpoint contract expects a learner_group-shaped state
        self.learner_group = _PPOLearnerShim(self)

    def _compile_update(self, loss_fn):
        cfg = self.config
        epochs = cfg.num_epochs
        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

        def epoch_body(carry, key_e):
            params, opt_state, batch = carry
            n = batch["obs"].shape[0]  # static at trace time
            # honor the configured minibatch size against the ACTUAL batch
            # (rollout_fragment_length * total envs), not train_batch_size
            n_mb = max(1, n // cfg.minibatch_size)
            perm = jax.random.permutation(key_e, n)
            shuffled = jax.tree.map(lambda x: x[perm], batch)
            mbs = jax.tree.map(
                lambda x: x[: (n // n_mb) * n_mb].reshape(n_mb, n // n_mb, *x.shape[1:]),
                shuffled,
            )

            def mb_body(c, mb):
                params, opt_state = c
                (loss, aux), grads = grad_fn(params, mb, None)
                updates, opt_state = self.optimizer.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                return (params, opt_state), dict(aux, total_loss=loss)

            (params, opt_state), metrics = jax.lax.scan(mb_body, (params, opt_state), mbs)
            return (params, opt_state, batch), metrics

        @jax.jit
        def update(params, opt_state, batch, key):
            keys = jax.random.split(key, epochs)
            (params, opt_state, _), metrics = jax.lax.scan(
                epoch_body, (params, opt_state, batch), keys
            )
            return params, opt_state, jax.tree.map(lambda m: m.mean(), metrics)

        return update

    def training_step(self) -> dict:
        cfg = self.config
        rollouts = self.env_runner_group.sample(self.params, cfg.rollout_fragment_length)
        batch = self.concat_rollouts(rollouts)
        T, B = batch["rewards"].shape
        self._timesteps += T * B

        final_vf = self._value_fn(self.params, batch["final_obs"])
        advs, targets = compute_gae(
            jnp.asarray(batch["rewards"]),
            jnp.asarray(batch["vf"]),
            final_vf,
            jnp.asarray(batch["terminateds"]),
            jnp.asarray(batch["truncateds"]),
            gamma=cfg.gamma,
            lam=cfg.lam,
        )
        flat = {
            "obs": batch["obs"].reshape(T * B, -1),
            "actions": batch["actions"].reshape(T * B, *batch["actions"].shape[2:]),
            "logp": batch["logp"].reshape(T * B),
            "vf_old": batch["vf"].reshape(T * B),
            "advantages": np.asarray(advs).reshape(T * B),
            "value_targets": np.asarray(targets).reshape(T * B),
        }
        flat = {k: jnp.asarray(v) for k, v in flat.items()}
        self.key, k = jax.random.split(self.key)
        self.params, self.opt_state, metrics = self._update(
            self.params, self.opt_state, flat, k
        )
        return {k2: float(v) for k2, v in metrics.items()}


class _PPOLearnerShim:
    """Adapts PPO's inlined learner state to the Algorithm checkpoint seam."""

    def __init__(self, algo: PPO):
        self.algo = algo

    def get_state(self) -> dict:
        a = self.algo
        return {
            "params": jax.device_get(a.params),
            "opt_state": jax.device_get(a.opt_state),
            "steps": a.iteration,
        }

    def set_state(self, state: dict) -> None:
        a = self.algo
        a.params = jax.device_put(state["params"])
        a.opt_state = jax.device_put(state["opt_state"])
