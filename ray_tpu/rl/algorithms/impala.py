"""IMPALA: asynchronous actor-learner with V-trace correction.

Reference analog: rllib/algorithms/impala/impala.py:568 training_step
(async EnvRunner sampling → aggregator actors → learner group; V-trace
in vtrace.py). TPU-first shape: env runners sample asynchronously with
slightly stale weights (the off-policy gap V-trace corrects); the
learner consumes whatever rollout refs have landed each step, and the
V-trace recurrence + update is one jitted program. Aggregation is the
object-store `wait` loop — no dedicated aggregator actor tier needed at
this scale because batches stage in host RAM, not GPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.core import api
from ray_tpu.rl.algorithm import Algorithm
from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.rl.learner import LearnerGroup
from ray_tpu.rl.postprocessing import compute_vtrace
from ray_tpu.rl.module import RLModuleSpec


class IMPALAConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=IMPALA)
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.clip_rho_threshold = 1.0
        self.clip_c_threshold = 1.0
        self.rollout_fragment_length = 32
        self.num_epochs = 1

    def training(self, **kwargs):
        for k in ("vf_loss_coeff", "entropy_coeff", "clip_rho_threshold", "clip_c_threshold"):
            if k in kwargs:
                setattr(self, k, kwargs.pop(k))
        return super().training(**kwargs)


class IMPALA(Algorithm):
    @classmethod
    def default_config(cls) -> IMPALAConfig:
        return IMPALAConfig()

    def build_components(self) -> None:
        cfg = self.config
        module = self.module_spec.build()
        self.module = module
        self.learner_group = LearnerGroup(
            self.module_spec,
            self._make_loss(module),
            num_learners=cfg.num_learners,
            lr=cfg.lr,
            grad_clip=cfg.grad_clip,
            seed=cfg.seed,
            # time-major batches: shard the env axis (1), keep T local for scans
            batch_axis=lambda name, leaf: 0 if name == "final_obs" else min(1, leaf.ndim - 1),
        )
        self._inflight: list = []

    def _vtrace_targets(self, module, params, batch, out, target_logp):
        """(vs, pg_adv) with gradient-free targets — the piece every
        V-trace algorithm shares (APPO subclasses swap only the
        surrogate, rl/algorithms/appo.py)."""
        cfg = self.config
        # targets must be gradient-free (reference vtrace computes them
        # outside the tape) — stop final_vf too, not just values/logp
        final_vf = jax.lax.stop_gradient(
            module.forward(params, batch["final_obs"])["vf"]
        )
        return compute_vtrace(
            batch["logp"],
            jax.lax.stop_gradient(target_logp),
            batch["rewards"],
            jax.lax.stop_gradient(out["vf"]),
            final_vf,
            batch["terminateds"],
            batch["truncateds"],
            gamma=cfg.gamma,
            clip_rho=cfg.clip_rho_threshold,
            clip_c=cfg.clip_c_threshold,
        )

    def _make_loss(self, module):
        cfg = self.config
        vf_coeff, ent_coeff = cfg.vf_loss_coeff, cfg.entropy_coeff

        def loss_fn(params, batch, _key):
            # batch is time-major [T, B, ...]
            out = module.forward(params, batch["obs"])
            target_logp = module.dist.logp(out["action_dist_inputs"], batch["actions"])
            vs, pg_adv = self._vtrace_targets(
                module, params, batch, out, target_logp
            )
            pg_loss = -(target_logp * pg_adv).mean()
            vf_loss = 0.5 * jnp.square(out["vf"] - vs).mean()
            entropy = module.dist.entropy(out["action_dist_inputs"]).mean()
            loss = pg_loss + vf_coeff * vf_loss - ent_coeff * entropy
            return loss, {"policy_loss": pg_loss, "vf_loss": vf_loss, "entropy": entropy}

        return loss_fn

    def training_step(self) -> dict:
        cfg = self.config
        # Host snapshot: the learner's device buffers get donated each update,
        # and runner actors are CPU-side anyway (multi-host ships bytes too).
        params = jax.device_get(self.learner_group.params)
        # Keep every runner busy: top up the in-flight sample set, then
        # consume whichever rollouts are ready (async actor-learner loop).
        want = max(1, cfg.num_env_runners)
        while len(self._inflight) < want:
            self._inflight.extend(
                self.env_runner_group.sample_async(params, cfg.rollout_fragment_length)
            )
        ready, self._inflight = api.wait(
            self._inflight, num_returns=max(1, len(self._inflight) // 2), timeout=30.0
        )
        rollouts = api.get(list(ready))
        metrics = {}
        for r in rollouts:
            T, B = r["rewards"].shape
            self._timesteps += T * B
            batch = {
                "obs": r["obs"],
                "actions": r["actions"],
                "logp": r["logp"],
                "rewards": r["rewards"],
                "terminateds": r["terminateds"].astype(np.float32),
                "truncateds": r["truncateds"].astype(np.float32),
                "final_obs": r["final_obs"],
            }
            metrics = self.learner_group.update(batch)
        return metrics
