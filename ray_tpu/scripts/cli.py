"""Operator CLI: start/stop/status for the cluster control plane.

Reference analog: `ray start` / `ray stop` / `ray status`
(python/ray/scripts/scripts.py:654) — head mode boots the GCS plus a
node daemon, worker mode joins an existing GCS, stop kills what this
host started, status prints the GCS's cluster view.

    python -m ray_tpu.scripts.cli start --head [--port 6380] \
        [--resources num_cpus=8,TPU=4] [--persist /var/lib/ray_tpu/gcs.snap]
    python -m ray_tpu.scripts.cli start --address HOST:PORT --resources ...
    python -m ray_tpu.scripts.cli status [--address HOST:PORT]
    python -m ray_tpu.scripts.cli stop
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from typing import Optional


def _state_dir() -> str:
    d = os.environ.get(
        "RAY_TPU_STATE_DIR",
        os.path.join(
            os.environ.get("TMPDIR", "/tmp"),
            f"ray_tpu-{os.environ.get('USER', 'user')}",
        ),
    )
    os.makedirs(d, exist_ok=True)
    return d


def _state_path() -> str:
    return os.path.join(_state_dir(), "cluster.json")


def _load_state() -> dict:
    try:
        with open(_state_path()) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return {"procs": []}


def _save_state(state: dict) -> None:
    with open(_state_path(), "w") as f:
        json.dump(state, f, indent=2)


def _read_banner(proc: subprocess.Popen, tag: str, timeout: float = 30.0) -> list:
    deadline = time.monotonic() + timeout
    assert proc.stdout is not None
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            raise RuntimeError(f"child exited before printing {tag}")
        line = line.strip()
        if line.startswith(tag):
            return line.split()[1:]
    raise RuntimeError(f"child did not print {tag} within {timeout}s")


def _spawn(cmd, env, log_name: str) -> subprocess.Popen:
    """Daemonized child: banner on a pipe we read then drop, logs to a
    file (NOT our inherited stderr — a captured CLI must reach EOF when
    the CLI exits, not when the daemons do)."""
    log_dir = os.path.join(_state_dir(), "logs")
    os.makedirs(log_dir, exist_ok=True)
    log = open(os.path.join(log_dir, log_name), "ab")
    return subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=log, text=True, env=env,
        start_new_session=True,
    )


def cmd_start(args) -> int:
    state = _load_state()
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")  # control plane never grabs a TPU
    if args.head:
        cmd = [
            sys.executable, "-m", "ray_tpu.cluster.gcs_service",
            "--host", args.host, "--port", str(args.port),
            "--death-timeout", str(args.death_timeout),
        ]
        if args.persist:
            cmd += ["--persist", args.persist]
        gcs = _spawn(cmd, env, "gcs.log")
        host_port = _read_banner(gcs, "GCS_ADDRESS")[0]
        gcs.stdout.close()
        state["gcs_address"] = host_port
        state["procs"].append({"role": "gcs", "pid": gcs.pid})
        print(f"GCS started at {host_port}")
        address = host_port
        if args.dashboard_port:
            cmd = [
                sys.executable, "-m", "ray_tpu.dashboard",
                "--gcs", address, "--host", args.host,
                "--port", str(args.dashboard_port),
            ]
            dash = _spawn(cmd, env, "dashboard.log")
            dash_addr = _read_banner(dash, "DASHBOARD_ADDRESS")[0]
            dash.stdout.close()
            state["procs"].append({"role": "dashboard", "pid": dash.pid})
            print(f"dashboard started at http://{dash_addr}")
    else:
        if not args.address:
            print("worker mode needs --address HOST:PORT", file=sys.stderr)
            return 2
        address = args.address
    if args.head or args.address:
        cmd = [
            sys.executable, "-m", "ray_tpu.cluster.node_daemon",
            "--gcs", address,
            "--resources", args.resources,
            "--host", args.host,
        ]
        if args.node_id:
            cmd += ["--node-id", args.node_id]
        if args.object_capacity:
            cmd += ["--object-capacity", str(args.object_capacity)]
        node = _spawn(cmd, env, "node.log")
        parts = _read_banner(node, "NODE_ADDRESS")
        node.stdout.close()
        state["procs"].append(
            {"role": "node", "pid": node.pid, "node_id": parts[1]}
        )
        print(f"node {parts[1]} started at {parts[0]}")
    _save_state(state)
    print(
        f"\nconnect with: ray_tpu.init(address=\"{address}\")\n"
        f"state file:   {_state_path()}"
    )
    return 0


def cmd_stop(args) -> int:
    state = _load_state()
    for rec in reversed(state.get("procs", [])):
        try:
            os.killpg(os.getpgid(rec["pid"]), signal.SIGTERM)
            print(f"stopped {rec['role']} (pid {rec['pid']})")
        except (ProcessLookupError, PermissionError, OSError):
            pass
    try:
        os.unlink(_state_path())
    except OSError:
        pass
    return 0


def cmd_status(args) -> int:
    address = args.address or _load_state().get("gcs_address")
    if not address:
        print("no cluster state found; pass --address HOST:PORT", file=sys.stderr)
        return 2
    from ray_tpu.cluster.rpc import RpcClient

    host, port = address.rsplit(":", 1)
    gcs = RpcClient(host, int(port), timeout=10.0).connect(retries=3)
    nodes = gcs.call("list_nodes", None)
    actors = gcs.call("list_actors", None)
    pgs = gcs.call("list_pgs", None)
    print(f"GCS: {address}")
    print(f"nodes ({len(nodes)}):")
    for n in nodes:
        mark = "ALIVE" if n["alive"] else "DEAD"
        avail = ", ".join(f"{k}={v:g}/{n['resources'].get(k, 0):g}"
                          for k, v in sorted(n["available"].items()))
        print(f"  {n['node_id']:<16} {mark:<6} {avail}")
    alive_actors = [a for a in actors if a["state"] != "DEAD"]
    print(f"actors: {len(alive_actors)} alive / {len(actors)} total")
    for a in alive_actors[:20]:
        name = a["name"] or a["actor_id"].hex()[:12]
        print(f"  {name:<24} {a['state']:<10} node={a['node_id']}")
    print(f"placement groups: {len(pgs)}")
    gcs.close()
    return 0


def cmd_submit(args) -> int:
    address = args.address or _load_state().get("gcs_address")
    if not address:
        print("no cluster state found; pass --address HOST:PORT", file=sys.stderr)
        return 2
    import shlex

    entry = list(args.entrypoint)
    if entry and entry[0] == "--":  # only the LEADING separator is ours
        entry = entry[1:]
    if not entry:
        print("submit needs an entrypoint command", file=sys.stderr)
        return 2
    from ray_tpu.job_submission import ClusterJobSubmissionClient, JobStatus

    renv: dict = {}
    if args.working_dir:
        renv["working_dir"] = args.working_dir
    env_vars = {}
    for kv in args.env:
        if "=" not in kv:
            print(f"--env expects K=V, got {kv!r}", file=sys.stderr)
            return 2
        k, v = kv.split("=", 1)
        env_vars[k] = v
    if env_vars:
        renv["env_vars"] = env_vars
    jc = ClusterJobSubmissionClient(address)
    sid = jc.submit_job(entrypoint=shlex.join(entry), runtime_env=renv or None)
    print(f"submitted {sid}")
    if args.no_wait:
        return 0
    st = jc.wait_until_finish(sid, timeout=24 * 3600)
    print(jc.get_job_logs(sid), end="")
    print(f"job {sid}: {st}")
    return 0 if st == JobStatus.SUCCEEDED else 1


def main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(prog="ray_tpu", description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)

    ps = sub.add_parser("start", help="start head (GCS+node) or join a cluster")
    ps.add_argument("--head", action="store_true")
    ps.add_argument("--address", default=None, help="existing GCS to join")
    ps.add_argument("--host", default="127.0.0.1")
    ps.add_argument("--port", type=int, default=0, help="GCS port (head mode)")
    ps.add_argument("--resources", default="num_cpus=1")
    ps.add_argument("--node-id", default=None)
    ps.add_argument("--persist", default=None, help="GCS snapshot path (FT)")
    ps.add_argument("--dashboard-port", type=int, default=0,
                    help="also start the dashboard on this port (head mode)")
    ps.add_argument("--object-capacity", type=int, default=None)
    ps.add_argument("--death-timeout", type=float, default=5.0)
    ps.set_defaults(fn=cmd_start)

    pt = sub.add_parser("stop", help="stop processes started on this host")
    pt.set_defaults(fn=cmd_stop)

    pu = sub.add_parser("status", help="print the cluster view")
    pu.add_argument("--address", default=None)
    pu.set_defaults(fn=cmd_status)

    pj = sub.add_parser(
        "submit", help="run a driver command ON the cluster (`ray job submit`)"
    )
    pj.add_argument("--address", default=None)
    pj.add_argument("--working-dir", default=None,
                    help="directory packaged to the cluster as the job cwd")
    pj.add_argument("--env", action="append", default=[],
                    metavar="K=V", help="environment for the driver")
    pj.add_argument("--no-wait", action="store_true",
                    help="return after submission instead of streaming status")
    pj.add_argument("entrypoint", nargs=argparse.REMAINDER,
                    help="command to run (prefix with -- to pass flags)")
    pj.set_defaults(fn=cmd_submit)

    from ray_tpu.scripts.k8s import cmd_k8s

    pk = sub.add_parser(
        "k8s", help="emit Kubernetes manifests (the KubeRay-operator role)"
    )
    pk.add_argument("--name", default="ray-tpu")
    pk.add_argument("--image", default="ray-tpu:latest")
    pk.add_argument("--namespace", default="default")
    pk.add_argument("--gcs-port", type=int, default=6379)
    pk.add_argument("--workers", type=int, default=2)
    pk.add_argument("--worker-resources", default="num_cpus=4")
    pk.add_argument("--worker-cpu", default=None,
                    help="pod cpu request (default: num_cpus from --worker-resources)")
    pk.add_argument("--worker-memory", default="8Gi")
    pk.add_argument("--tpu-workers", type=int, default=0)
    pk.add_argument("--tpu-accelerator", default="v5e-8")
    pk.add_argument("--tpu-chips-per-host", type=int, default=4)
    pk.set_defaults(fn=cmd_k8s)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
