"""Kubernetes deployment generator: the KubeRay operator's surface,
collapsed to manifests.

Reference analog: the KubeRay RayCluster CRD (head group + worker
groups, rayStartParams) that the reference's docs/tooling target. There
is no custom controller here — a head Deployment + Service and plain
worker Deployments reconcile the same topology with stock Kubernetes
controllers, and the TPU worker group maps to a nodeSelector +
`google.com/tpu` resource requests (slice-gang scheduling stays in the
framework's placement groups, core/accelerators.py).

`ray_tpu k8s --workers N [--worker-cpu 8 --worker-memory 16Gi]` prints
YAML to stdout; pipe to kubectl apply.
"""

from __future__ import annotations

from typing import Optional


def _container(name: str, image: str, command: list, resources: Optional[dict],
               env: Optional[dict] = None) -> dict:
    c: dict = {"name": name, "image": image, "command": command}
    if resources:
        c["resources"] = {"requests": dict(resources), "limits": dict(resources)}
    if env:
        c["env"] = [{"name": k, "value": str(v)} for k, v in env.items()]
    return c


def generate_manifests(
    name: str = "ray-tpu",
    image: str = "ray-tpu:latest",
    namespace: str = "default",
    gcs_port: int = 6379,
    workers: int = 2,
    worker_resources: str = "num_cpus=4",
    worker_cpu: Optional[str] = None,
    worker_memory: str = "8Gi",
    tpu_workers: int = 0,
    tpu_accelerator: str = "v5e-8",
    tpu_chips_per_host: int = 4,
) -> list:
    """Returns a list of Kubernetes manifest dicts (Service, head
    Deployment, worker Deployment, optional TPU worker Deployment)."""
    labels = {"app": name}
    head_labels = {**labels, "ray-tpu-role": "head"}
    gcs_addr = f"{name}-head.{namespace}.svc:{gcs_port}"
    if worker_cpu is None:
        # pod requests must match what the daemon advertises to the
        # scheduler, or leases over-commit the cgroup. Keep the float
        # form — k8s accepts fractional cpu quantities ("0.5"); int
        # truncation would request cpu:0 for sub-core daemons
        cpus = "4"
        for kv in worker_resources.split(","):
            if kv.startswith("num_cpus="):
                cpus = kv.split("=", 1)[1]
        worker_cpu = cpus

    service = {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": f"{name}-head", "namespace": namespace,
                     "labels": labels},
        "spec": {
            "selector": head_labels,
            "ports": [
                {"name": "gcs", "port": gcs_port, "targetPort": gcs_port},
                {"name": "dashboard", "port": 8265, "targetPort": 8265},
            ],
        },
    }

    head = {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": f"{name}-head", "namespace": namespace,
                     "labels": head_labels},
        "spec": {
            "replicas": 1,
            "selector": {"matchLabels": head_labels},
            "template": {
                "metadata": {"labels": head_labels},
                "spec": {
                    "containers": [
                        {
                            **_container(
                                "head", image,
                                ["python", "-m", "ray_tpu.scripts.cli", "start",
                                 "--head", "--host", "0.0.0.0",
                                 "--port", str(gcs_port),
                                 "--dashboard-port", "8265",
                                 "--persist", "/var/lib/ray-tpu/gcs.snapshot",
                                 "--resources", "num_cpus=2"],
                                {"cpu": "2", "memory": "4Gi"},
                            ),
                            "volumeMounts": [
                                {"name": "gcs-state",
                                 "mountPath": "/var/lib/ray-tpu"}
                            ],
                        }
                    ],
                    # swap for a PVC to survive pod RESCHEDULING; emptyDir
                    # already survives container restarts in place, which
                    # is what --persist protects against on one node
                    "volumes": [{"name": "gcs-state", "emptyDir": {}}],
                },
            },
        },
    }

    worker_labels = {**labels, "ray-tpu-role": "worker"}
    worker = {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": f"{name}-worker", "namespace": namespace,
                     "labels": worker_labels},
        "spec": {
            "replicas": workers,
            "selector": {"matchLabels": worker_labels},
            "template": {
                "metadata": {"labels": worker_labels},
                "spec": {
                    "containers": [
                        _container(
                            "worker", image,
                            ["python", "-m", "ray_tpu.scripts.cli", "start",
                             "--address", gcs_addr,
                             "--host", "0.0.0.0",
                             "--resources", worker_resources],
                            {"cpu": worker_cpu, "memory": worker_memory},
                        )
                    ],
                },
            },
        },
    }

    out = [service, head, worker]
    if tpu_workers > 0:
        tpu_labels = {**labels, "ray-tpu-role": "tpu-worker"}
        # scheduler resources come from --worker-resources (daemon
        # vocabulary), NOT the pod-cpu quantity (k8s vocabulary — may be
        # "3500m", which the daemon's float parse rejects)
        tpu_res = f"{worker_resources},TPU={tpu_chips_per_host}"
        out.append({
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": {"name": f"{name}-tpu-worker", "namespace": namespace,
                         "labels": tpu_labels},
            "spec": {
                "replicas": tpu_workers,
                "selector": {"matchLabels": tpu_labels},
                "template": {
                    "metadata": {"labels": tpu_labels},
                    "spec": {
                        "nodeSelector": {
                            "cloud.google.com/gke-tpu-accelerator": tpu_accelerator,
                        },
                        "containers": [
                            _container(
                                "tpu-worker", image,
                                ["python", "-m", "ray_tpu.scripts.cli", "start",
                                 "--address", gcs_addr,
                                 "--host", "0.0.0.0",
                                 "--resources", tpu_res],
                                {"cpu": worker_cpu, "memory": worker_memory,
                                 "google.com/tpu": str(tpu_chips_per_host)},
                            )
                        ],
                    },
                },
            },
        })
    return out


def manifests_yaml(**kwargs) -> str:
    import yaml

    return "---\n".join(
        yaml.safe_dump(m, sort_keys=False) for m in generate_manifests(**kwargs)
    )


def cmd_k8s(args) -> int:
    print(manifests_yaml(
        name=args.name,
        image=args.image,
        namespace=args.namespace,
        gcs_port=args.gcs_port,
        workers=args.workers,
        worker_resources=args.worker_resources,
        worker_cpu=args.worker_cpu,
        worker_memory=args.worker_memory,
        tpu_workers=args.tpu_workers,
        tpu_accelerator=args.tpu_accelerator,
        tpu_chips_per_host=args.tpu_chips_per_host,
    ), end="")
    return 0
