"""Learner -> rollout weight publishing over the fabric transfer plane.

The second client of ``fabric.transport.send_arrays`` (the first is the
disaggregated KV handoff), and the missing piece ROADMAP item 5 names:
"Podracer architectures for scalable RL" (PAPERS.md) decouples actor
and learner pools on one pod, which makes weight sync a *device-array
move between pools* — exactly the shape of a KV handoff, so it rides
the same plane instead of growing a second bespoke one.

``WeightPublisher`` (learner side) flattens a params pytree and ships
the leaves as one versioned bundle per rollout endpoint;
``WeightSubscriber`` (rollout side) polls its endpoint between
generation rounds, verifies the bundle's device checksum, and swaps the
serving engine's params **bitwise** (params are jit *arguments*
throughout llm/engine.py, never closed-over constants, so a swap takes
effect on the very next step with zero recompiles for same-shape
leaves). Versions are monotonic: a delayed older publish landing after
a newer one is dropped, never applied backwards.

Leaf order is the pytree's own deterministic ``tree_leaves`` order; the
subscriber unflattens against the *receiving* engine's tree structure,
so the treedef itself never needs to cross the wire (both sides hold a
same-architecture params tree, the precondition weight sync has
anyway). A leaf-count mismatch fails loudly — a silent partial apply
would serve a chimera model.
"""

from __future__ import annotations

from typing import Any, Optional

from ray_tpu.fabric.transport import DeviceTransport, FabricTransferError
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.train.weight_sync")


class WeightSyncError(Exception):
    """A publish failed, arrived corrupt, or doesn't match the
    subscriber's params structure."""


def _leaf_key(i: int) -> str:
    return f"w{i:05d}"  # fixed width: sorted() order == leaf order


class WeightPublisher:
    """Learner-side: publish a params pytree to rollout endpoints."""

    def __init__(self, transport: Optional[DeviceTransport] = None,
                 namespace: str = "weights"):
        # owns the transport iff it constructed it: close() then removes
        # the registered endpoints from the process-global plane (each
        # queue can pin up to endpoint_capacity full params copies on
        # device — an abandoned publisher must not leak that forever)
        self._owns_transport = transport is None
        self.transport = transport or DeviceTransport(namespace=namespace)
        self._version = 0
        self.num_published = 0
        # most recent successfully-published params tree, retained for
        # late joiners (r20 autoscale cold start): a replica scaled up
        # from zero streams THESE weights at the same version — no
        # checkpoint path, no learner round-trip
        self._latest_params: Any = None

    def register_rollout(self, endpoint_id: str, device: Any = None) -> tuple:
        """Bind one rollout engine's receive endpoint (pass the engine's
        param/cache device so the put lands where generation reads)."""
        return self.transport.register_endpoint(endpoint_id, device=device)

    def publish(self, params: Any, targets: list,
                version: Optional[int] = None,
                timeout_s: float = 30.0) -> int:
        """Ship ``params`` to every target as one sealed device bundle;
        returns the published version (monotonic when auto-assigned)."""
        import jax

        leaves = jax.tree_util.tree_leaves(params)
        if version is None:
            self._version += 1
            version = self._version
        else:
            self._version = max(self._version, int(version))
        arrays = {_leaf_key(i): leaf for i, leaf in enumerate(leaves)}
        meta = {"version": int(version), "num_leaves": len(leaves)}
        for target in targets:
            try:
                self.transport.send_arrays(
                    target, arrays, meta=meta, timeout_s=timeout_s,
                    bundle_id=f"weights-v{version}",
                )
            except FabricTransferError as e:
                raise WeightSyncError(
                    f"weight publish v{version} to {target!r} failed: {e}"
                ) from e
        self.num_published += 1
        self._latest_params = params
        return int(version)

    @property
    def latest_version(self) -> int:
        return self._version

    def publish_latest(self, target, timeout_s: float = 30.0) -> int:
        """Re-publish the most recent bundle to ONE late-joining endpoint
        at the SAME version (a cold-started replica catching up to the
        fleet). Raises WeightSyncError before any publish has happened —
        a cold start with nothing to stream is a deployment bug, not a
        silent fresh-weights replica."""
        if self._latest_params is None:
            raise WeightSyncError(
                "publish_latest: no publish retained yet — nothing to "
                "stream to a late joiner"
            )
        return self.publish(
            self._latest_params, [target],
            version=self._version, timeout_s=timeout_s,
        )

    def close(self) -> None:
        if self._owns_transport:
            self.transport.close()


class WeightSubscriber:
    """Rollout-side: poll for publishes, apply the newest to an engine."""

    def __init__(self, transport: DeviceTransport, endpoint_id: str):
        self.transport = transport
        self.endpoint_id = endpoint_id
        self.version = 0          # newest applied (or held) version
        self.num_applied = 0
        self.num_stale_dropped = 0
        self.num_corrupt_dropped = 0

    def poll(self, timeout_s: float = 0.05):
        """Drain the endpoint; returns the newest verified (version,
        leaves) newer than anything seen, or None. Corrupt bundles are
        counted and dropped (the learner's next publish supersedes —
        weight sync is idempotent by version, there is nothing to
        re-prefill)."""
        newest = None
        while True:
            b = self.transport.recv_arrays(self.endpoint_id,
                                           timeout_s=timeout_s)
            if b is None:
                break
            timeout_s = 0.0  # only the first wait blocks; then drain
            if not b.verify():
                self.num_corrupt_dropped += 1
                logger.warning("dropping corrupt weight bundle %r",
                               b.bundle_id)
                continue
            v = int(b.meta.get("version", 0))
            if v <= self.version or (newest and v <= newest[0]):
                self.num_stale_dropped += 1
                continue
            leaves = [b.arrays[k] for k in sorted(b.arrays)]
            if len(leaves) != int(b.meta.get("num_leaves", len(leaves))):
                self.num_corrupt_dropped += 1
                continue
            newest = (v, leaves)
        return newest

    def apply_to_engine(self, engine: Any, timeout_s: float = 0.05) -> Optional[int]:
        """Poll and, if a newer version arrived, swap ``engine.params``
        in place (unflattened against the engine's own tree structure).
        Returns the applied version or None. Callers swap between
        generation rounds — mid-request decode keeps reading the old
        tree it was dispatched with until the next step picks this up."""
        import jax

        got = self.poll(timeout_s=timeout_s)
        if got is None:
            return None
        version, leaves = got
        treedef = jax.tree_util.tree_structure(engine.params)
        if treedef.num_leaves != len(leaves):
            raise WeightSyncError(
                f"weight bundle v{version} has {len(leaves)} leaves, "
                f"engine params tree has {treedef.num_leaves} — "
                "publisher and rollout engine disagree on architecture"
            )
        engine.params = jax.tree_util.tree_unflatten(treedef, leaves)
        # sealed prefix KV was computed with the OLD weights: a hit
        # against it after the swap would splice stale keys/values into
        # new-weight attention. Running requests keep their own
        # refcounted blocks (they finish on the weights they started
        # with); only the zero-ref reuse pool is dropped. Invalidation
        # must CASCADE through every tier (engine.drop_prefix_cache:
        # HBM + host DRAM + object store + this engine's prefix-index
        # rows) — dropping HBM alone would let a post-swap request
        # resurrect pre-swap K/V from a deeper tier.
        drop = getattr(engine, "drop_prefix_cache", None)
        if drop is not None:
            drop()
        else:
            allocator = getattr(engine, "allocator", None)
            if allocator is not None:
                allocator.drop_prefix_cache()
        self.version = version
        self.num_applied += 1
        # surface the applied version on the engine itself: stats() /
        # GET /v1/stats report it, so actor/learner version skew is
        # observable from the serving surface (rl/post_train status)
        try:
            engine.weight_version = int(version)
        except Exception:  # noqa: BLE001 — read-only engine stub
            pass
        return version

    def stats(self) -> dict:
        return {
            "endpoint": self.endpoint_id,
            "version": self.version,
            "num_applied": self.num_applied,
            "num_stale_dropped": self.num_stale_dropped,
            "num_corrupt_dropped": self.num_corrupt_dropped,
        }

    def close(self) -> None:
        """Drop any queued bundles; the transport (publisher-owned)
        outlives the subscriber, so only the backlog is drained here."""
        try:
            while self.transport.recv_arrays(self.endpoint_id,
                                             timeout_s=0.0) is not None:
                pass
        except FabricTransferError:
            pass  # endpoint already gone (publisher closed first)
