"""Checkpoints: directory handles + sharded-array save/restore.

Analog of the reference ray.train.Checkpoint
(python/ray/train/_checkpoint.py — a directory handle on storage) with
the TPU-native twist promised in SURVEY.md §5.4: sharded jax arrays are
written per-shard via orbax (async-capable), so a multi-host gang
checkpoints without gathering to one host. Plain python state falls back
to pickle in the same directory.
"""

from __future__ import annotations

import os
import pickle
import shutil
import tempfile
import threading
from typing import Any, Optional

_ORBAX_SUBDIR = "sharded_state"
_PICKLE_FILE = "state.pkl"


class Checkpoint:
    """A directory handle. Create with `from_directory`, read with
    `to_directory` / `as_directory` (reference Checkpoint API surface)."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    def to_directory(self, dest: Optional[str] = None) -> str:
        if dest is None or os.path.abspath(dest) == self.path:
            return self.path
        shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    def as_directory(self):
        import contextlib

        @contextlib.contextmanager
        def cm():
            yield self.path

        return cm()

    # -- typed helpers -------------------------------------------------------

    @classmethod
    def from_state(cls, state: Any, path: str, sharded: bool = False) -> "Checkpoint":
        os.makedirs(path, exist_ok=True)
        if sharded:
            save_sharded(state, os.path.join(path, _ORBAX_SUBDIR))
        else:
            with open(os.path.join(path, _PICKLE_FILE), "wb") as f:
                pickle.dump(state, f)
        return cls(path)

    def load_state(self, template: Any = None) -> Any:
        orbax_dir = os.path.join(self.path, _ORBAX_SUBDIR)
        if os.path.isdir(orbax_dir):
            return restore_sharded(orbax_dir, template)
        with open(os.path.join(self.path, _PICKLE_FILE), "rb") as f:
            return pickle.load(f)

    def __repr__(self):
        return f"Checkpoint({self.path})"


def save_sharded(state: Any, path: str, wait: bool = True):
    """Write a pytree of (possibly sharded) jax arrays with orbax. Each host
    writes only its shards; async unless wait=True."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    if os.path.exists(path):
        shutil.rmtree(path)
    ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
    ckptr.save(path, args=ocp.args.StandardSave(state))
    if wait:
        ckptr.wait_until_finished()
        ckptr.close()
        return None
    return ckptr  # caller must wait_until_finished()/close()


def restore_sharded(path: str, template: Any = None) -> Any:
    """Restore; with a template of jax.ShapeDtypeStructs carrying shardings,
    arrays come back sharded onto the mesh without a host gather."""
    import orbax.checkpoint as ocp

    ckptr = ocp.Checkpointer(ocp.StandardCheckpointHandler())
    try:
        if template is not None:
            return ckptr.restore(path, args=ocp.args.StandardRestore(template))
        return ckptr.restore(path)
    finally:
        ckptr.close()


class CheckpointManager:
    """Retention policy over reported checkpoints (reference
    CheckpointConfig.num_to_keep semantics)."""

    def __init__(self, root: str, num_to_keep: Optional[int] = None,
                 score_attribute: Optional[str] = None, score_order: str = "max"):
        self.root = root
        self.num_to_keep = num_to_keep
        self.score_attribute = score_attribute
        self.score_order = score_order
        self._ckpts: list[tuple[float, int, Checkpoint]] = []
        self._seq = 0
        self._lock = threading.Lock()
        os.makedirs(root, exist_ok=True)

    def register(self, ckpt: Checkpoint, metrics: Optional[dict] = None) -> None:
        with self._lock:
            score = 0.0
            if self.score_attribute and metrics:
                score = float(metrics.get(self.score_attribute, 0.0))
                if self.score_order == "min":
                    score = -score
            self._seq += 1
            self._ckpts.append((score, self._seq, ckpt))
            if self.num_to_keep is not None and len(self._ckpts) > self.num_to_keep:
                # evict lowest score (or oldest) WITHOUT reordering the
                # registration-ordered list — latest() must stay the most
                # recent checkpoint, it drives failure-resume
                if self.score_attribute:
                    evicted = min(self._ckpts, key=lambda t: (t[0], t[1]))
                    self._ckpts.remove(evicted)
                else:
                    evicted = self._ckpts.pop(0)
                shutil.rmtree(evicted[2].path, ignore_errors=True)

    def latest(self) -> Optional[Checkpoint]:
        with self._lock:
            return max(self._ckpts, key=lambda t: t[1])[2] if self._ckpts else None

    def best(self) -> Optional[Checkpoint]:
        with self._lock:
            if not self._ckpts:
                return None
            return max(self._ckpts, key=lambda t: (t[0], t[1]))[2]

    def new_checkpoint_dir(self) -> str:
        with self._lock:
            self._seq += 1
            return os.path.join(self.root, f"checkpoint_{self._seq:06d}")
