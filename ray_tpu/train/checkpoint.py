"""Checkpoints: directory handles + sharded-array save/restore.

Analog of the reference ray.train.Checkpoint
(python/ray/train/_checkpoint.py — a directory handle on storage) with
the TPU-native twist promised in SURVEY.md §5.4: sharded jax arrays are
written per-shard via orbax (async-capable), so a multi-host gang
checkpoints without gathering to one host. Plain python state falls back
to pickle in the same directory.

Crash-atomicity (r12): every write lands in a ``<path>.tmp`` staging
directory and is ``os.rename``d into place only when complete — a rank
killed mid-save (the elastic trainer's common case) leaves a ``.tmp``
residue, never a half-written checkpoint a resume could load.
``latest_complete`` / ``prune_partial`` are the restore-side guards:
partial directories are skipped AND deleted so they can't shadow a good
checkpoint or accumulate across recoveries.
"""

from __future__ import annotations

import contextlib
import os
import pickle
import shutil
import tempfile
import threading
from typing import Any, Optional

_ORBAX_SUBDIR = "sharded_state"
_PICKLE_FILE = "state.pkl"
_PARTIAL_SUFFIX = ".tmp"
_OLD_SUFFIX = ".old"


def _swap_into_place(tmp: str, dest: str) -> None:
    """Install a fully-written staging dir at ``dest`` without a window
    where a crash loses BOTH checkpoints: the previous ``dest`` is
    renamed aside (not rmtree'd) before the staging dir renames in, so
    every crash point leaves at least one complete checkpoint on disk —
    ``prune_partial`` renames an orphaned ``.old`` back on restore."""
    old = dest + _OLD_SUFFIX
    if os.path.exists(dest):
        # a stale .old alongside a live dest means the last swap
        # completed — safe to drop. An ORPHANED .old (dest missing,
        # e.g. a retry after a crash mid-swap) is the only complete
        # copy and must survive until the new dest is installed.
        if os.path.exists(old):
            shutil.rmtree(old)
        os.rename(dest, old)
    os.rename(tmp, dest)
    if os.path.exists(old):
        shutil.rmtree(old, ignore_errors=True)


class Checkpoint:
    """A directory handle. Create with `from_directory`, read with
    `to_directory` / `as_directory` (reference Checkpoint API surface)."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    def to_directory(self, dest: Optional[str] = None) -> str:
        if dest is None or os.path.abspath(dest) == self.path:
            return self.path
        shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    def as_directory(self):
        import contextlib

        @contextlib.contextmanager
        def cm():
            yield self.path

        return cm()

    # -- typed helpers -------------------------------------------------------

    @classmethod
    def from_state(cls, state: Any, path: str, sharded: bool = False) -> "Checkpoint":
        """Crash-atomic: the whole checkpoint is staged in ``path.tmp``
        and renamed into place — readers either see a complete
        checkpoint at ``path`` or nothing."""
        path = os.path.abspath(path)
        tmp = path + _PARTIAL_SUFFIX
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        if sharded:
            save_sharded(state, os.path.join(tmp, _ORBAX_SUBDIR))
        else:
            with open(os.path.join(tmp, _PICKLE_FILE), "wb") as f:
                pickle.dump(state, f)
        _swap_into_place(tmp, path)
        return cls(path)

    def load_state(self, template: Any = None) -> Any:
        orbax_dir = os.path.join(self.path, _ORBAX_SUBDIR)
        if os.path.isdir(orbax_dir):
            return restore_sharded(orbax_dir, template)
        with open(os.path.join(self.path, _PICKLE_FILE), "rb") as f:
            return pickle.load(f)

    def __repr__(self):
        return f"Checkpoint({self.path})"


class _PendingSave:
    """Handle for an in-flight async sharded save: the staged ``.tmp``
    directory is renamed into place only in ``wait_until_finished`` —
    before that the destination either holds the previous checkpoint or
    nothing, never a torn write."""

    def __init__(self, ckptr, tmp: str, dest: str):
        self._ckptr = ckptr
        self._tmp = tmp
        self._dest = dest
        self._finalized = False

    def wait_until_finished(self) -> None:
        self._ckptr.wait_until_finished()
        if not self._finalized:
            self._finalized = True
            _swap_into_place(self._tmp, self._dest)

    def close(self) -> None:
        self.wait_until_finished()
        self._ckptr.close()


def save_sharded(state: Any, path: str, wait: bool = True):
    """Write a pytree of (possibly sharded) jax arrays with orbax. Each host
    writes only its shards; async unless wait=True. Crash-atomic: orbax
    writes into ``path.tmp`` and the rename to ``path`` happens only
    after the write completed (a killed rank leaves ``.tmp`` residue,
    pruned on restore, never a partial checkpoint)."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    tmp = path + _PARTIAL_SUFFIX
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
    ckptr.save(tmp, args=ocp.args.StandardSave(state))
    pending = _PendingSave(ckptr, tmp, path)
    if wait:
        pending.close()
        return None
    return pending  # caller must wait_until_finished()/close()


def is_complete(path: str) -> bool:
    """A checkpoint directory is complete iff it was renamed into place
    (not a ``.tmp`` staging dir or a ``.old`` swap residue) and carries
    a payload."""
    if (
        path.endswith(_PARTIAL_SUFFIX)
        or path.endswith(_OLD_SUFFIX)
        or not os.path.isdir(path)
    ):
        return False
    return (
        os.path.isdir(os.path.join(path, _ORBAX_SUBDIR))
        or os.path.isfile(os.path.join(path, _PICKLE_FILE))
    )


def prune_partial(root: str) -> list:
    """Delete ``.tmp`` staging residue (and payload-less checkpoint
    directories) a killed rank left under ``root``; returns the pruned
    paths. Safe to call while a save is in flight elsewhere ONLY on a
    fresh restore path — which is exactly when it runs."""
    pruned = []
    if not os.path.isdir(root):
        return pruned
    for name in sorted(os.listdir(root)):
        p = os.path.join(root, name)
        if not os.path.isdir(p):
            continue
        if name.endswith(_OLD_SUFFIX):
            # swap residue: a crash between _swap_into_place's renames
            # leaves the previous good checkpoint aside as .old with
            # nothing at the base path — rename it back (sorted order
            # guarantees the base, if present, was already visited).
            # With the base present the swap completed; drop the residue.
            base = p[: -len(_OLD_SUFFIX)]
            if os.path.exists(base):
                shutil.rmtree(p, ignore_errors=True)
                pruned.append(p)
            else:
                os.rename(p, base)
            continue
        if name.endswith(_PARTIAL_SUFFIX) or (
            name.startswith("checkpoint_") and not is_complete(p)
        ):
            shutil.rmtree(p, ignore_errors=True)
            pruned.append(p)
    return pruned


def latest_complete(root: str) -> Optional["Checkpoint"]:
    """Newest COMPLETE ``checkpoint_*`` directory under ``root`` (the
    cold-resume entry point: partial dirs are pruned, never loaded)."""
    prune_partial(root)
    if not os.path.isdir(root):
        return None
    names = sorted(
        n for n in os.listdir(root)
        if n.startswith("checkpoint_") and is_complete(os.path.join(root, n))
    )
    return Checkpoint(os.path.join(root, names[-1])) if names else None


def restore_sharded(path: str, template: Any = None) -> Any:
    """Restore; with a template of jax.ShapeDtypeStructs carrying shardings,
    arrays come back sharded onto the mesh without a host gather."""
    import orbax.checkpoint as ocp

    ckptr = ocp.Checkpointer(ocp.StandardCheckpointHandler())
    try:
        if template is not None:
            return ckptr.restore(path, args=ocp.args.StandardRestore(template))
        return ckptr.restore(path)
    finally:
        ckptr.close()


class CheckpointManager:
    """Retention policy over reported checkpoints (reference
    CheckpointConfig.num_to_keep semantics)."""

    def __init__(self, root: str, num_to_keep: Optional[int] = None,
                 score_attribute: Optional[str] = None, score_order: str = "max"):
        self.root = root
        self.num_to_keep = num_to_keep
        self.score_attribute = score_attribute
        self.score_order = score_order
        self._ckpts: list[tuple[float, int, Checkpoint]] = []
        self._lock = threading.Lock()
        # path currently being restored from: num_to_keep eviction must
        # never delete it out from under the restore (the elastic
        # trainer registers new checkpoints while older recoveries may
        # still be reading the one they resumed from)
        self._restoring: Optional[str] = None
        os.makedirs(root, exist_ok=True)
        # resume the dir sequence past what is already on disk: a fresh
        # manager over an old root (cold resume after a driver crash)
        # must never hand out a checkpoint_NNNNNN name that from_state
        # would then rmtree out from under latest_complete
        self._seq = max(
            (
                int(n[len("checkpoint_"):])
                for n in os.listdir(root)
                if n.startswith("checkpoint_")
                and n[len("checkpoint_"):].isdigit()
            ),
            default=0,
        )

    def register(self, ckpt: Checkpoint, metrics: Optional[dict] = None) -> None:
        with self._lock:
            score = 0.0
            if self.score_attribute and metrics:
                score = float(metrics.get(self.score_attribute, 0.0))
                if self.score_order == "min":
                    score = -score
            self._seq += 1
            self._ckpts.append((score, self._seq, ckpt))
            if self.num_to_keep is not None and len(self._ckpts) > self.num_to_keep:
                # evict lowest score (or oldest) WITHOUT reordering the
                # registration-ordered list — latest() must stay the most
                # recent checkpoint, it drives failure-resume. The
                # checkpoint being restored is pinned: evict the next
                # candidate instead (briefly keeping num_to_keep + 1).
                candidates = [
                    t for t in self._ckpts if t[2].path != self._restoring
                ]
                if not candidates:
                    return
                if self.score_attribute:
                    evicted = min(candidates, key=lambda t: (t[0], t[1]))
                else:
                    evicted = candidates[0]
                self._ckpts.remove(evicted)
                shutil.rmtree(evicted[2].path, ignore_errors=True)

    def mark_restoring(self, ckpt: Optional[Checkpoint]) -> None:
        """Pin ``ckpt`` against num_to_keep eviction for the duration of
        a restore (pass None to unpin)."""
        with self._lock:
            self._restoring = ckpt.path if ckpt is not None else None

    @contextlib.contextmanager
    def restoring(self, ckpt: Checkpoint):
        """Context manager form of the restore pin."""
        self.mark_restoring(ckpt)
        try:
            yield ckpt
        finally:
            self.mark_restoring(None)

    def latest(self) -> Optional[Checkpoint]:
        with self._lock:
            return max(self._ckpts, key=lambda t: t[1])[2] if self._ckpts else None

    def best(self) -> Optional[Checkpoint]:
        with self._lock:
            if not self._ckpts:
                return None
            return max(self._ckpts, key=lambda t: (t[0], t[1]))[2]

    def new_checkpoint_dir(self) -> str:
        with self._lock:
            self._seq += 1
            return os.path.join(self.root, f"checkpoint_{self._seq:06d}")
