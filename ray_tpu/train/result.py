"""Training result (analog of reference python/ray/air/result.py)."""

from __future__ import annotations

import dataclasses
from typing import Optional

from ray_tpu.train.checkpoint import Checkpoint


@dataclasses.dataclass
class Result:
    metrics: dict
    checkpoint: Optional[Checkpoint]
    path: str
    error: Optional[BaseException] = None
    metrics_history: list = dataclasses.field(default_factory=list)

    @property
    def best_checkpoint(self) -> Optional[Checkpoint]:
        return self.checkpoint
