from ray_tpu.train import session
from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager, restore_sharded, save_sharded
from ray_tpu.train.config import CheckpointConfig, FailureConfig, RunConfig, ScalingConfig
from ray_tpu.train.result import Result
from ray_tpu.train.step import TrainState, init_sharded_params, make_train_step
from ray_tpu.train.trainer import JaxTrainer

__all__ = [
    "Checkpoint",
    "CheckpointConfig",
    "CheckpointManager",
    "FailureConfig",
    "JaxTrainer",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "TrainState",
    "init_sharded_params",
    "make_train_step",
    "restore_sharded",
    "save_sharded",
    "session",
]
