from ray_tpu.train import session
from ray_tpu.train.checkpoint import (
    Checkpoint,
    CheckpointManager,
    latest_complete,
    prune_partial,
    restore_sharded,
    save_sharded,
)
from ray_tpu.train.config import CheckpointConfig, FailureConfig, RunConfig, ScalingConfig
from ray_tpu.train.elastic import (
    ElasticConfig,
    ElasticResult,
    Recovery,
    TrainerSupervisor,
    rng_for,
)
from ray_tpu.train.result import Result
from ray_tpu.train.step import TrainState, init_sharded_params, make_train_step
from ray_tpu.train.trainer import JaxTrainer

__all__ = [
    "Checkpoint",
    "CheckpointConfig",
    "CheckpointManager",
    "ElasticConfig",
    "ElasticResult",
    "FailureConfig",
    "JaxTrainer",
    "Recovery",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "TrainState",
    "TrainerSupervisor",
    "init_sharded_params",
    "latest_complete",
    "make_train_step",
    "prune_partial",
    "restore_sharded",
    "rng_for",
    "save_sharded",
    "session",
]
