"""ray_tpu.train.elastic — fault-tolerant gang training.

The serving path survives everything ``ray_tpu.chaos`` injects (r09);
this module closes the same loop for the trainer. A
``TrainerSupervisor`` drives a data-parallel gang whose in-loop
allreduce rides ``ray_tpu.collective`` — the plane the r12 chaos kinds
(``KILL_RANK``, ``STALL_COLLECTIVE``, ``DROP_COLLECTIVE``,
``PARTIAL_PARTITION``) break — and recovers from every one of them:

 1. **detect**: every collective op is bounded (collective/errors.py),
    so a dead/stalled/partitioned rank surfaces as a typed
    ``CollectiveError`` (or the victim's ``RankKilled``) within the
    step timeout instead of hanging the pod;
 2. **abort**: ``abort_collective_group`` wakes every survivor blocked
    in the broken round immediately — nobody burns the full timeout
    waiting on a rank already known dead;
 3. **re-form**: the gang re-joins the SAME group name at gang epoch
    ``gen + 1`` — with a replacement rank (same world size) when
    allowed, else shrunk toward ``min_world_size``. The generation
    guard makes zombies harmless: a stale rank's collective ops raise
    ``StaleGenerationError`` and its late deposits land under old-gen
    keys nobody reads — it can never inject gradients into the new
    gang;
 4. **restore**: state comes back from the last complete checkpoint
    (``train/checkpoint.py`` — crash-atomic, partial dirs pruned);
 5. **resume**: batches derive ONLY from ``(seed, step, world_size,
    rank)`` via a counter-based seed stream, so resuming at the same
    world size is loss-identical to the uninterrupted run (gated by
    ``benchmarks/train_chaos_bench.py`` → ``TRAIN_chaos_r12.json``).

Observability: recoveries run under a ``train.recovery`` obs span and
move the ``ray_tpu_train_gang_epoch`` gauge /
``ray_tpu_train_recoveries_total`` + ``ray_tpu_train_ranks_lost_total``
counters (telemetry-aggregated, so ``ray_tpu status`` shows trainer
health next to the pool SLOs).

"Podracer architectures for scalable RL" (PAPERS.md) assumes exactly
this: decoupled pools that survive pool churn; "Exploring the limits of
Concurrency in ML Training on Google TPUs" motivates keeping the
recovery cost bounded (detect within the step timeout, restore only
what the checkpoint cadence lost).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Optional

import numpy as np

from ray_tpu.chaos.harness import RankKilled
from ray_tpu.collective import (
    CollectiveAbortedError,
    CollectiveError,
    CollectivePartitionError,
    CollectiveTimeoutError,
    StaleGenerationError,
    abort_collective_group,
    declare_collective_group,
    destroy_collective_group,
)
from ray_tpu.cluster.client import ActorDiedError as ClusterActorDiedError
from ray_tpu.cluster.client import ClusterTaskError
from ray_tpu.core import api
from ray_tpu.core.errors import (
    ActorDiedError,
    ActorUnavailableError,
    TaskError,
    WorkerCrashedError,
)
from ray_tpu.train.checkpoint import (
    Checkpoint,
    CheckpointManager,
    latest_complete,
)
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.train.elastic")


# -- observability ------------------------------------------------------------


def register_metrics() -> dict:
    """Trainer-health metrics (scripts/check_metrics.py hook). All three
    are telemetry-aggregated: the gang epoch rolls up as MAX (the
    fleet's current generation), the counters as SUM.

    Constructed per call, not cached (the obs/slo.py convention):
    same-name re-registration shares storage in util/metrics, and
    re-constructing means a test's ``clear_registry()`` can never strand
    a stale cached instance writing to storage the exporter no longer
    renders. These fire once per recovery, not per step."""
    from ray_tpu.obs.telemetry import AGG_MAX, cluster_counter, cluster_gauge

    _METRICS: dict = {}
    _METRICS["gang_epoch"] = cluster_gauge(
        "ray_tpu_train_gang_epoch",
        description="elastic trainer: current gang epoch (generation) — "
        "bumps on every recovery re-form; zombie ranks of older epochs "
        "are refused by the collective generation guard",
        agg=AGG_MAX,
    )
    _METRICS["recoveries"] = cluster_counter(
        "ray_tpu_train_recoveries_total",
        description="elastic trainer: completed gang recoveries "
        "(abort -> re-form -> checkpoint restore -> resume)",
    )
    _METRICS["ranks_lost"] = cluster_counter(
        "ray_tpu_train_ranks_lost_total",
        description="elastic trainer: ranks lost to kill/stall/partition "
        "across all recoveries",
    )
    _METRICS["blackouts"] = cluster_counter(
        "ray_tpu_train_blackouts_total",
        description="elastic trainer: control-plane blackouts ridden out "
        "(GCS dark -> wait -> resume; no ranks blamed, no recovery "
        "budget burned)",
    )
    return _METRICS


# -- deterministic seed stream ------------------------------------------------


def rng_for(seed: int, step: int, rank: int = 0) -> np.random.Generator:
    """The trainer's seed stream: a counter-based generator keyed ONLY by
    ``(seed, step, rank)`` — no global RNG state to checkpoint, so a
    resume replays the exact batch sequence of the uninterrupted run."""
    return np.random.default_rng(
        np.random.SeedSequence(entropy=int(seed), spawn_key=(int(step), int(rank)))
    )


# -- gradient packing ---------------------------------------------------------


def _pack(loss: float, grads: Any) -> tuple[np.ndarray, Any, list]:
    """[loss, flat grads] as one float64 vector — one allreduce per step,
    and rank-ordered float64 summation so the reduced result is bitwise
    deterministic (the loss-identity contract depends on it)."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(grads)
    arrs = [np.asarray(leaf) for leaf in leaves]
    flat = [np.ravel(a).astype(np.float64) for a in arrs]
    vec = np.concatenate([np.asarray([loss], np.float64)] + flat) if flat else (
        np.asarray([loss], np.float64)
    )
    shapes = [(a.shape, a.dtype) for a in arrs]
    return vec, treedef, shapes


def _unpack(vec: np.ndarray, treedef, shapes) -> tuple[float, Any]:
    import jax

    loss = float(vec[0])
    leaves = []
    off = 1
    for shape, dtype in shapes:
        n = int(np.prod(shape)) if shape else 1
        leaves.append(vec[off:off + n].reshape(shape).astype(dtype))
        off += n
    return loss, jax.tree_util.tree_unflatten(treedef, leaves)


# -- the gang member ----------------------------------------------------------


@api.remote
class _ElasticRank:
    """One gang member. Holds the replicated state; each step computes
    local gradients on its deterministic shard, allreduces
    ``[loss, grads]``, applies the mean — so every rank ends every step
    with identical state and rank 0's copy is THE checkpoint."""

    def __init__(self, grad_fn, apply_fn, batch_fn, seed: int,
                 group_name: str, step_timeout_s: float, backend: str):
        self._grad_fn = grad_fn
        self._apply_fn = apply_fn
        self._batch_fn = batch_fn
        self._seed = int(seed)
        self._group = group_name
        self._timeout = float(step_timeout_s)
        self._backend = backend
        self._state: Any = None
        self._rank = -1
        self._world = 0
        self._gen = -1

    def join(self, world_size: int, rank: int, gen: int) -> bool:
        """(Re-)join the gang at a gang epoch: recovery re-forms the SAME
        group name at gen + 1, superseding (and waking) the old one."""
        from ray_tpu.collective import init_collective_group

        init_collective_group(
            world_size, rank, backend=self._backend,
            group_name=self._group, gen=gen,
        )
        self._rank, self._world, self._gen = rank, world_size, gen
        return True

    def set_state(self, state: Any) -> bool:
        self._state = state
        return True

    def get_state(self) -> Any:
        return self._state

    def run_steps(self, start_step: int, n_steps: int) -> list:
        """Run ``n_steps`` data-parallel steps; returns per-step mean
        losses. Any gang fault surfaces as a typed error within the
        step timeout — never a hang."""
        from ray_tpu.collective import allreduce

        losses = []
        for step in range(start_step, start_step + n_steps):
            batch = self._batch_fn(self._seed, step, self._world, self._rank)
            loss, grads = self._grad_fn(self._state, batch)
            vec, treedef, shapes = _pack(float(loss), grads)
            total = allreduce(
                vec, group_name=self._group, rank=self._rank,
                timeout=self._timeout,
            )
            mean_loss, mean_grads = _unpack(
                np.asarray(total, np.float64) / self._world, treedef, shapes
            )
            self._state = self._apply_fn(self._state, mean_grads)
            losses.append(mean_loss)
        return losses


# -- supervisor ---------------------------------------------------------------


@dataclasses.dataclass
class ElasticConfig:
    """Knobs of the recovery loop."""

    world_size: int = 2
    min_world_size: int = 1
    group_name: str = "elastic"
    backend: str = "host"          # "host" (thread gang) | "cluster"
    seed: int = 0
    step_timeout_s: float = 15.0   # bound on every collective op
    steps_per_round: int = 1       # steps dispatched per supervision round
    checkpoint_every: int = 10     # steps between checkpoints
    num_to_keep: Optional[int] = 3
    max_recoveries: int = 8
    allow_replacement: bool = True  # spawn a fresh rank vs shrink
    sharded_checkpoints: bool = True  # orbax path vs pickle
    # control-plane blackout contract (r13): when the probe says the GCS
    # itself is dark, a failed round is NOBODY's fault — the supervisor
    # parks (bounded) until the plane answers again, re-forms the SAME
    # gang at gen+1, restores, and resumes. No rank is killed, nothing
    # lands in `recoveries`, and max_recoveries is untouched: a blackout
    # may only cost scheduling freshness, never gang health.
    control_plane_probe: Optional[Callable[[], bool]] = None
    # optional restart detector: sampled before each round and again at
    # fault time — a CHANGED value means the control plane restarted
    # during the round (the typed errors often only surface once the
    # plane answers again, when a probe would already say "fine"), which
    # is a blackout even if the plane is back up by classification time
    control_plane_epoch: Optional[Callable[[], Any]] = None
    blackout_wait_s: float = 60.0   # bound on waiting for the GCS to return
    blackout_poll_s: float = 0.25   # probe cadence while waiting
    max_blackouts: int = 8          # flap bound; beyond it, normal recovery

    def __post_init__(self):
        if not 1 <= self.min_world_size <= self.world_size:
            raise ValueError(
                f"need 1 <= min_world_size <= world_size, got "
                f"{self.min_world_size}/{self.world_size}"
            )
        if self.checkpoint_every < 1 or self.steps_per_round < 1:
            raise ValueError("checkpoint_every/steps_per_round must be >= 1")


@dataclasses.dataclass
class Recovery:
    """Post-mortem record of one recovery."""

    step: int              # first step of the aborted round
    resumed_from: int      # step the checkpoint restored to
    gen: int               # gang epoch AFTER the re-form
    world_size: int        # world size AFTER the re-form
    ranks_lost: int
    cause: str             # rank_killed | stall | partition | rank_died
    detect_s: float        # fault -> all survivors unblocked
    recover_s: float       # fault -> training resumed


@dataclasses.dataclass
class ElasticResult:
    state: Any
    losses: list           # per-step mean loss, full run
    recoveries: list       # [Recovery]
    completed: bool
    final_gen: int
    final_world_size: int
    checkpoint: Optional[Checkpoint] = None
    error: Optional[BaseException] = None
    # control-plane blackouts ridden out (Recovery records with
    # cause="control_plane_blackout", ranks_lost=0) — deliberately NOT
    # in `recoveries`: a dark GCS is never attributed to the gang
    blackouts: list = dataclasses.field(default_factory=list)


def _classify(err: BaseException) -> Optional[str]:
    """Fault taxonomy for a failed rank ref. Returns None for errors that
    mean 'collateral of someone else's fault' (aborted round, stale
    generation, a survivor's own expired wait) — those ranks SURVIVED."""
    # both actor runtimes wrap task-side exceptions with the original in
    # .cause (in-process TaskError, cluster ClusterTaskError) — unwrap
    # the whole chain and classify the raiser's exception, else every
    # cluster-backend fault misreads as rank death (innocent teardown)
    seen: set[int] = set()
    while id(err) not in seen:
        seen.add(id(err))
        cause = getattr(err, "cause", None)
        if isinstance(err, (TaskError, ClusterTaskError)) and isinstance(
            cause, BaseException
        ):
            err = cause
        else:
            break
    if isinstance(err, RankKilled):
        return "rank_killed"
    if isinstance(err, CollectivePartitionError):
        return "partition"
    if isinstance(err, (ActorDiedError, ActorUnavailableError,
                        WorkerCrashedError, ClusterActorDiedError)):
        return "rank_died"
    if isinstance(err, (CollectiveAbortedError, StaleGenerationError)):
        return None
    if isinstance(err, CollectiveTimeoutError):
        # a rank whose own wait expired is a SURVIVOR of a peer's fault
        # (the faulty rank raises kill/partition in its own frame)
        return None
    if isinstance(err, CollectiveError):
        return "collective_error"
    return "rank_died"  # unknown actor-side failure: treat as lost


class TrainerSupervisor:
    """Detect -> abort -> re-form -> restore -> resume, until
    ``total_steps`` complete or the recovery budget is spent.

    ``grad_fn(state, batch) -> (loss, grads)``,
    ``apply_fn(state, mean_grads) -> state``,
    ``batch_fn(seed, step, world_size, rank) -> batch`` (must be pure in
    its arguments — that purity IS the deterministic-resume contract),
    ``init_fn(seed) -> state``.
    """

    def __init__(
        self,
        *,
        init_fn: Callable[[int], Any],
        grad_fn: Callable[[Any, Any], tuple],
        apply_fn: Callable[[Any, Any], Any],
        batch_fn: Callable[[int, int, int, int], Any],
        total_steps: int,
        checkpoint_root: str,
        config: Optional[ElasticConfig] = None,
        on_round: Optional[Callable[[int, Callable[[], Any]], None]] = None,
    ):
        self._init_fn = init_fn
        self._grad_fn = grad_fn
        self._apply_fn = apply_fn
        self._batch_fn = batch_fn
        # post-round hook ``on_round(step, state_fn)``: called after
        # every SUCCESSFUL round with the step just completed and a
        # zero-or-one-fetch state thunk (the checkpoint fetch is reused
        # when the round also checkpointed). This is how a consumer
        # wires the gang's post-step state into an external plane —
        # e.g. ``WeightPublisher.publish`` for RL post-training
        # (rl/post_train) — without coupling the supervisor to it. Hook
        # exceptions are logged and swallowed: a broken downstream
        # plane must never fault a healthy gang.
        self._on_round = on_round
        self._total_steps = int(total_steps)
        self._cfg = config or ElasticConfig()
        self._root = checkpoint_root
        self._manager = CheckpointManager(
            checkpoint_root, num_to_keep=self._cfg.num_to_keep
        )
        self._metrics = register_metrics()
        self._workers: list = []
        self._gen = 0
        self._world = self._cfg.world_size
        self._last_faults: dict[int, BaseException] = {}
        self.recoveries: list[Recovery] = []
        self.blackouts: list[Recovery] = []

    # -- gang lifecycle -------------------------------------------------------

    def _spawn_gang(self, world: int, gen: int, state: Any,
                    survivors: Optional[list] = None) -> None:
        """(Re-)form the gang: reuse healthy survivors, spawn the rest,
        everyone joins at ``gen`` and loads ``state``."""
        cfg = self._cfg
        # ranks join from their own processes, so the supervisor must
        # DECLARE the gang or its abort_collective_group/
        # destroy_collective_group calls no-op for a cluster backend
        # (no local group object, GCS abort marker never published,
        # leaked gen key poisons the next run of this group name)
        declare_collective_group(world, cfg.backend, cfg.group_name)
        pool = list(survivors or [])
        while len(pool) < world:
            pool.append(_ElasticRank.remote(
                self._grad_fn, self._apply_fn, self._batch_fn, cfg.seed,
                cfg.group_name, cfg.step_timeout_s, cfg.backend,
            ))
        self._workers = pool[:world]
        api.get(
            [w.join.remote(world, rank, gen)
             for rank, w in enumerate(self._workers)],
            timeout=60,
        )
        api.get([w.set_state.remote(state) for w in self._workers], timeout=60)
        self._gen = gen
        self._world = world
        self._metrics["gang_epoch"].set(float(gen))

    def _teardown(self) -> None:
        for w in self._workers:
            try:
                api.kill(w)
            except Exception:  # noqa: BLE001
                pass
        self._workers = []

    def _fetch_state(self) -> Any:
        """Every rank ends every step with identical state, so ANY
        healthy rank's copy is THE checkpoint — a rank that died after
        the round completed must not crash the fetch (its death is
        detected and recovered at the next dispatch)."""
        last: Optional[BaseException] = None
        for w in self._workers:
            try:
                return api.get(w.get_state.remote(), timeout=60)
            except BaseException as e:  # noqa: BLE001
                last = e
        raise last if last is not None else RuntimeError("gang is empty")

    # -- checkpointing --------------------------------------------------------

    def _save(self, state: Any, step: int) -> None:
        ckpt = Checkpoint.from_state(
            {"state": state, "step": np.asarray(step, np.int64)},
            self._manager.new_checkpoint_dir(),
            sharded=self._cfg.sharded_checkpoints,
        )
        self._manager.register(ckpt, {"step": step})

    def _restore(self) -> tuple[Any, int]:
        """State + step to resume from: the latest complete checkpoint
        (pinned against num_to_keep eviction while loading), else
        a fresh init at step 0."""
        ckpt = self._manager.latest() or latest_complete(self._root)
        if ckpt is None:
            return self._init_fn(self._cfg.seed), 0
        with self._manager.restoring(ckpt):
            doc = ckpt.load_state()
        return doc["state"], int(np.asarray(doc["step"]))

    # -- control-plane blackout -----------------------------------------------

    def _control_plane_ok(self) -> bool:
        """True when the GCS answers (or no probe is configured — then
        blackout handling is off and every fault takes the normal path)."""
        probe = self._cfg.control_plane_probe
        if probe is None:
            return True
        try:
            return bool(probe())
        except Exception:  # noqa: BLE001 — a probe failure IS "dark"
            return False

    def _await_control_plane(self) -> bool:
        """Park until the probe answers again (bounded). True = the plane
        returned within blackout_wait_s."""
        deadline = time.monotonic() + self._cfg.blackout_wait_s
        while time.monotonic() < deadline:
            if self._control_plane_ok():
                return True
            time.sleep(self._cfg.blackout_poll_s)
        return False

    # distinct from None ("no detector configured"): the detector exists
    # but the plane would not answer — i.e. it was DARK at sample time
    _EPOCH_UNREADABLE = object()

    def _plane_epoch(self) -> Any:
        fn = self._cfg.control_plane_epoch
        if fn is None:
            return None
        try:
            return fn()
        except Exception:  # noqa: BLE001 — unreadable IS a signal
            return self._EPOCH_UNREADABLE

    def _blackout_detected(self, epoch_before: Any) -> bool:
        """A fault round is a control-plane blackout when the plane is
        dark RIGHT NOW, when it was already dark at round START (epoch
        unreadable — the blackout began before the round did), or when
        it restarted during the round (epoch changed — the typed errors
        often surface only once the redial succeeds, i.e. after the
        plane already returned)."""
        if self._cfg.control_plane_probe is None:
            return False
        if not self._control_plane_ok():
            return True
        if epoch_before is None:
            return False  # no restart detector configured
        if epoch_before is self._EPOCH_UNREADABLE:
            return True  # the round was dispatched into a dark plane
        epoch_after = self._plane_epoch()
        if epoch_after is None or epoch_after is self._EPOCH_UNREADABLE:
            return False  # probe says fine but detector flaky: no claim
        return epoch_after != epoch_before

    # -- supervision ----------------------------------------------------------

    def _drive_round(self, step: int, n: int) -> tuple[Optional[list], list, float]:
        """One dispatch of ``n`` steps across the gang. Returns
        (rank0 losses | None on fault, lost worker handles, detect_s)."""
        refs = [w.run_steps.remote(step, n) for w in self._workers]
        by_ref = {id(r): i for i, r in enumerate(refs)}
        pending = set(refs)
        results: dict[int, list] = {}
        faults: dict[int, BaseException] = {}
        # generous outer bound: the collective timeout is the real
        # detector; this only guards a rank wedged OUTSIDE a collective
        deadline = time.monotonic() + n * self._cfg.step_timeout_s + 60.0
        t_fault = None
        wedged: set[int] = set()
        while pending:
            ready, _ = api.wait(list(pending), num_returns=1, timeout=0.2)
            for ref in ready:
                pending.discard(ref)
                rank = by_ref[id(ref)]
                try:
                    results[rank] = api.get(ref)
                except BaseException as e:  # noqa: BLE001
                    faults[rank] = e
                    if t_fault is None:
                        t_fault = time.monotonic()
                        # unblock every survivor still parked in the
                        # broken round NOW — the abort primitive. Best
                        # effort: with the control plane dark the marker
                        # can't publish, and the bounded op timeouts are
                        # the backstop
                        try:
                            abort_collective_group(
                                self._cfg.group_name,
                                f"rank {rank} fault at step {step}: {e!r}",
                            )
                        except Exception:  # noqa: BLE001
                            pass
            if pending and time.monotonic() > deadline:
                try:
                    abort_collective_group(self._cfg.group_name, "round deadline")
                except Exception:  # noqa: BLE001
                    pass
                for ref in pending:
                    rank = by_ref[id(ref)]
                    wedged.add(rank)
                    faults.setdefault(
                        rank,
                        CollectiveTimeoutError(
                            f"rank {rank} never returned from round at "
                            f"step {step}",
                            group=self._cfg.group_name, gen=self._gen,
                            rank=rank,
                        ),
                    )
                break
        if not faults:
            return results[0], [], 0.0
        detect_s = time.monotonic() - t_fault if t_fault is not None else 0.0
        # a rank whose own bounded wait expired is a survivor of a peer's
        # fault — but a rank that never RETURNED by the round deadline is
        # wedged outside the collective plane (e.g. a hung grad_fn) and
        # must be replaced: reusing it would queue the recovery join
        # behind its stuck call
        lost = [
            self._workers[rank]
            for rank, err in faults.items()
            if _classify(err) is not None or rank in wedged
        ]
        self._last_faults = faults
        return None, lost, detect_s

    def fit(self) -> ElasticResult:
        cfg = self._cfg
        state, step = self._restore()
        losses: list = [None] * self._total_steps
        self._spawn_gang(self._world, self._gen, state)
        error: Optional[BaseException] = None
        try:
            while step < self._total_steps:
                n = min(cfg.steps_per_round, self._total_steps - step)
                epoch_before = self._plane_epoch()
                round_losses, lost_workers, detect_s = self._drive_round(step, n)
                if round_losses is not None:
                    for i, lv in enumerate(round_losses):
                        losses[step + i] = lv
                    step += n
                    # checkpoint when this round CROSSED a cadence
                    # boundary (not only when it landed exactly on one —
                    # steps_per_round need not divide checkpoint_every)
                    fetched: Optional[Any] = None
                    if (
                        step // cfg.checkpoint_every
                        > (step - n) // cfg.checkpoint_every
                        or step >= self._total_steps
                    ):
                        state = self._fetch_state()
                        self._save(state, step)
                        fetched = state
                    if self._on_round is not None:
                        state_fn = (
                            (lambda s=fetched: s) if fetched is not None
                            else self._fetch_state
                        )
                        try:
                            self._on_round(step, state_fn)
                        except Exception:  # noqa: BLE001 — hook faults stay downstream
                            logger.warning(
                                "on_round hook failed at step %d", step,
                                exc_info=True,
                            )
                    continue
                # -- recovery -------------------------------------------------
                faults = self._last_faults
                causes = {
                    c for c in (_classify(e) for e in faults.values()) if c
                }
                # no rank actually lost (every fault is a timeout/abort
                # collateral): a peer stalled past the bound or a
                # contribution was dropped — same recovery, full gang
                cause = next(
                    (c for c in ("rank_killed", "rank_died", "partition",
                                 "collective_error") if c in causes),
                    "stall",
                )
                # -- control-plane blackout: wait-and-resume, blame nobody
                if (
                    len(self.blackouts) < cfg.max_blackouts
                    and self._blackout_detected(epoch_before)
                ):
                    from ray_tpu.obs.recorder import span as _span

                    t0 = time.monotonic()
                    with _span("train.blackout", attrs={
                        "group": cfg.group_name, "step": str(step),
                        "gen": str(self._gen),
                    }):
                        logger.warning(
                            "train.blackout: control plane dark at step %d; "
                            "parking (no ranks blamed, budget untouched)",
                            step,
                        )
                        if self._await_control_plane():
                            # every rank survived — re-form the SAME gang
                            # at gen+1 (the aborted round poisoned this
                            # epoch), restore, resume deterministically
                            fault_step = step
                            state, step = self._restore()
                            try:
                                self._spawn_gang(
                                    self._world, self._gen + 1, state,
                                    survivors=list(self._workers),
                                )
                            except BaseException:  # noqa: BLE001
                                self._teardown()
                                self._spawn_gang(
                                    self._world, self._gen + 2, state
                                )
                            self._metrics["blackouts"].inc()
                            rec = Recovery(
                                step=fault_step, resumed_from=step,
                                gen=self._gen, world_size=self._world,
                                ranks_lost=0,
                                cause="control_plane_blackout",
                                detect_s=round(detect_s, 4),
                                recover_s=round(time.monotonic() - t0, 4),
                            )
                            self.blackouts.append(rec)
                            logger.warning(
                                "train.blackout: plane returned after "
                                "%.2fs; resumed from step %d at gen %d",
                                rec.recover_s, step, self._gen,
                            )
                            continue
                    # the plane never came back within blackout_wait_s:
                    # this is a real outage, not a blip — surface it
                    error = next(iter(faults.values()))
                    break
                if len(self.recoveries) >= cfg.max_recoveries:
                    error = next(iter(faults.values()))
                    break
                if len(self.recoveries) >= 2 and all(
                    r.step == step and r.cause == cause
                    for r in self.recoveries[-2:]
                ):
                    # third consecutive IDENTICAL fault trace: batches
                    # are deterministic in (seed, step, rank), so this
                    # is a bug that replays from the checkpoint (e.g. a
                    # grad_fn exception), not pod weather — recovery
                    # cannot fix it; stop instead of burning the rest of
                    # the budget on restore-replay-crash cycles
                    error = next(iter(faults.values()))
                    break
                t0 = time.monotonic()
                from ray_tpu.obs.recorder import span

                with span("train.recovery", attrs={
                    "group": cfg.group_name, "gen": str(self._gen + 1),
                    "cause": cause, "step": str(step),
                    "ranks_lost": str(len(lost_workers)),
                }):
                    survivors = [
                        w for w in self._workers if w not in lost_workers
                    ]
                    for w in lost_workers:
                        try:
                            api.kill(w)
                        except Exception:  # noqa: BLE001
                            pass
                    if cfg.allow_replacement:
                        new_world = self._world
                    else:
                        new_world = max(cfg.min_world_size, len(survivors))
                    if len(survivors) < cfg.min_world_size and not cfg.allow_replacement:
                        error = next(iter(faults.values()))
                        break
                    fault_step = step
                    state, step = self._restore()
                    try:
                        self._spawn_gang(
                            new_world, self._gen + 1, state,
                            survivors=survivors,
                        )
                    except BaseException:  # noqa: BLE001
                        # a survivor died mid-re-form: drop everyone and
                        # build a fresh gang one epoch further on (the
                        # partial gang may have published gen + 1)
                        self._teardown()
                        self._spawn_gang(new_world, self._gen + 2, state)
                    self._metrics["recoveries"].inc()
                    self._metrics["ranks_lost"].inc(float(len(lost_workers)))
                    rec = Recovery(
                        step=fault_step, resumed_from=step, gen=self._gen,
                        world_size=new_world, ranks_lost=len(lost_workers),
                        cause=cause, detect_s=round(detect_s, 4),
                        recover_s=round(time.monotonic() - t0, 4),
                    )
                    self.recoveries.append(rec)
                    logger.warning(
                        "train.recovery: %s at step %d -> gen %d world %d "
                        "(resumed from step %d, %d lost)",
                        cause, rec.step, rec.gen, rec.world_size,
                        rec.resumed_from, rec.ranks_lost,
                    )
            completed = step >= self._total_steps and error is None
            if completed:
                state = self._fetch_state()
            return ElasticResult(
                state=state,
                losses=losses[:step],
                recoveries=list(self.recoveries),
                completed=completed,
                final_gen=self._gen,
                final_world_size=self._world,
                checkpoint=self._manager.latest(),
                error=error,
                blackouts=list(self.blackouts),
            )
        finally:
            self._teardown()
            try:
                destroy_collective_group(cfg.group_name)
            except Exception:  # noqa: BLE001
                pass
