"""JaxTrainer: gang-orchestrated SPMD training.

The DataParallelTrainer equivalent (reference:
python/ray/train/data_parallel_trainer.py:26 DataParallelTrainer →
BackendExecutor _internal/backend_executor.py:69 → WorkerGroup
_internal/worker_group.py:102), built in the Train-v2 controller style
(train/v2/_internal/execution/controller/controller.py:91: a state
machine polling the worker gang, consulting failure policy between
iterations) — with the torch/NCCL bootstrap replaced by the TPU-native
backend: each worker is one host of the gang; `backend_setup` runs
jax.distributed-style bootstrap (on one host: nothing — the mesh IS the
communicator), and in-loop collectives are XLA ops in the user's jitted
step.
"""

from __future__ import annotations

from typing import Callable, Optional

from ray_tpu.core import api, errors
from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.config import FailureConfig, RunConfig, ScalingConfig
from ray_tpu.train.result import Result
from ray_tpu.train import session as session_mod
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.train")


@api.remote(num_cpus=0)
class _ReportChannel:
    """Controller<->gang mailbox. An ACTOR (not a shared Queue/Event) so
    the same trainer drives thread workers in-process AND cluster worker
    processes (reference: session.report travels worker->controller as
    an actor round-trip, train/_internal/session.py:405)."""

    def __init__(self):
        self._reports: list = []
        self._base = 0  # global index of _reports[0]
        self._stop = False

    def put(self, rep: dict) -> bool:
        self._reports.append(rep)
        return self._stop  # piggyback the stop flag on the report ack

    def drain(self, cursor: int = 0) -> list:
        # cursor = number of reports the controller has consumed. Reports
        # at/above the cursor are returned (NOT popped — a timed-out get
        # retries without losing checkpoints); reports below it are acked
        # and pruned so a long run can't grow the channel unboundedly.
        acked = max(0, min(cursor - self._base, len(self._reports)))
        if acked:
            del self._reports[:acked]
            self._base += acked
        return self._reports[max(0, cursor - self._base):]

    def stop(self) -> bool:
        self._stop = True
        return True


class _QueueProxy:
    """Worker-side file of the channel: duck-types queue.put for the
    session; remembers the stop flag the controller piggybacks back."""

    def __init__(self, channel):
        self._channel = channel
        self._stopped = False

    def put(self, rep: dict) -> None:
        ref = self._channel.put.remote(rep)
        self._stopped = bool(api.get(ref))
        try:
            # worker processes BORROW refs (no auto-free); without this a
            # long run leaks one stored ack object per report
            api.free(ref)
        except Exception:
            pass

    def is_set(self) -> bool:  # also serves as the stop_event
        return self._stopped


@api.remote
class _TrainWorker:
    """One gang member (1 per host). Runs the user loop under a session."""

    def __init__(self, rank: int, world_size: int, trial_dir: str, channel,
                 profile: bool = False):
        proxy = _QueueProxy(channel)
        self.ctx = session_mod.TrainContext(
            world_rank=rank,
            world_size=world_size,
            trial_dir=trial_dir,
            report_queue=proxy,
            stop_event=proxy,
            profile=profile,
        )

    def reserve_coordinator(self, port=None) -> str:
        """Rank 0 only: pick the jax.distributed coordinator address on
        THIS host (the MASTER_ADDR election of train/torch/config.py:153,
        done via the gang's own worker 0 instead of an env var)."""
        from ray_tpu.parallel.distributed import reserve_coordinator_address

        return reserve_coordinator_address(port=port)

    def setup_distributed(self, coordinator: str, num_processes: int,
                          process_id: int, config) -> bool:
        """Run the jax.distributed bootstrap in this worker process.

        Must happen before the user loop touches a backend; afterwards
        jax.devices() spans the whole gang (reference analog:
        _TorchBackend.on_start, train/torch/config.py:115)."""
        from ray_tpu.parallel.distributed import initialize_gang_member

        initialize_gang_member(coordinator, num_processes, process_id, config)
        return True

    def set_resume_checkpoint(self, ckpt) -> bool:
        self.ctx.latest_checkpoint = ckpt
        return True

    def set_dataset_shards(self, shards: dict) -> bool:
        self.ctx.dataset_shards = shards
        return True

    def run(self, fn: Callable, config: dict) -> str:
        session_mod._set_session(self.ctx)
        try:
            fn(config) if _wants_arg(fn) else fn()
            return "done"
        except StopIteration:
            return "stopped"
        finally:
            session_mod._clear_session()


def _wants_arg(fn: Callable) -> bool:
    import inspect

    try:
        return len(inspect.signature(fn).parameters) > 0
    except (TypeError, ValueError):
        return True


class JaxTrainer:
    """Run `train_loop_per_worker` on a gang of workers.

    Inside the loop, user code uses ray_tpu.train.session (report /
    get_checkpoint / get_world_rank) and builds its mesh over the host's
    devices (ray_tpu.parallel.make_mesh). For a pod slice, set
    scaling_config.pod_type and the gang maps 1 worker per slice host via
    the slice placement group.
    """

    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[dict] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[dict] = None,
        backend_config=None,  # JaxDistributedConfig for multi-host SPMD
        profile: bool = False,
    ):
        self._fn = train_loop_per_worker
        self._config = train_loop_config or {}
        self._scaling = scaling_config or ScalingConfig()
        self._run_config = run_config or RunConfig()
        self._datasets = datasets or {}
        self._backend_config = backend_config
        # profile=True: workers see session.profiling_enabled() and the
        # controller publishes rank-0 report cadence to the metrics
        # registry (ray_tpu.profiler observability surfaces)
        self._profile = profile

    # -- controller ----------------------------------------------------------

    def fit(self) -> Result:
        trial_dir = self._run_config.resolved_storage_path()
        ckpt_cfg = self._run_config.checkpoint_config
        manager = CheckpointManager(
            trial_dir,
            ckpt_cfg.num_to_keep,
            ckpt_cfg.checkpoint_score_attribute,
            ckpt_cfg.checkpoint_score_order,
        )
        failure_cfg = self._run_config.failure_config
        failures = 0
        resume_ckpt: Optional[Checkpoint] = None
        # metrics/history accumulate ACROSS attempts (a restart continues the
        # same logical run, reference Train-v2 controller semantics)
        history: list[dict] = []
        last_metrics: dict = {}

        while True:
            try:
                outcome, error = self._run_attempt(
                    trial_dir, manager, resume_ckpt, history, last_metrics
                )
            except BaseException as e:  # noqa: BLE001 - setup failure (e.g. infeasible gang)
                outcome, error = "failed", e
            if outcome == "ok":
                return Result(
                    metrics=dict(last_metrics),
                    checkpoint=manager.latest(),
                    path=trial_dir,
                    metrics_history=history,
                )
            failures += 1
            if failure_cfg.max_failures >= 0 and failures > failure_cfg.max_failures:
                return Result(
                    metrics=dict(last_metrics),
                    checkpoint=manager.latest(),
                    path=trial_dir,
                    error=error,
                    metrics_history=history,
                )
            resume_ckpt = manager.latest()
            logger.warning(
                "train attempt failed (%s); restarting gang (failure %d/%s)",
                error, failures, failure_cfg.max_failures,
            )
            if self._scaling.min_workers:
                # elastic: the failed attempt's leases release asynchronously
                # and the availability view refreshes by heartbeat — POLL for
                # capacity recovery (bounded) instead of guessing a sleep, or
                # the next gang would collapse toward min_workers spuriously
                import time as _time

                # stop early when capacity STABILIZES (two equal readings):
                # a permanently lost node must not cost the full bound on
                # every restart
                deadline = _time.monotonic() + 10.0
                prev = -1
                while _time.monotonic() < deadline:
                    size = self._gang_size()
                    if size >= self._scaling.num_workers or size == prev:
                        break
                    prev = size
                    _time.sleep(0.5)

    def _gang_size(self) -> int:
        """Elastic sizing: the largest gang in [min_workers, num_workers]
        the cluster can place right now (Train-v2 scaling_policy seam)."""
        n = self._scaling.num_workers
        mn = self._scaling.min_workers
        if not mn or mn >= n:
            return n
        req = self._scaling.worker_resources()
        try:
            avail = api.available_resources()
        except Exception:
            return n
        fits = n
        for k, v in req.items():
            if v <= 0:
                continue
            # cluster naming vs in-process naming for the CPU resource
            a = avail.get(k, avail.get("num_cpus" if k == "CPU" else k, 0.0))
            fits = min(fits, int(a // v))
        return max(mn, min(n, fits))

    def _run_attempt(self, trial_dir, manager, resume_ckpt, history, last_metrics):
        n = self._gang_size()
        if n < self._scaling.num_workers:
            logger.warning(
                "elastic gang: sizing down to %d/%d workers (cluster capacity)",
                n, self._scaling.num_workers,
            )
        channel = None
        cursor = [0]
        report_hist = None
        last_report_t = [None]
        if self._profile:
            from ray_tpu.util.metrics import Histogram

            report_hist = Histogram(
                "train_report_interval_ms",
                description="profiler: wall time between rank-0 session "
                "reports (the training loop's step cadence)",
                boundaries=[1, 5, 10, 50, 100, 500, 1000, 5000, 10000, 60000],
            )

        def drain():
            if channel is None:
                return
            try:
                reports = api.get(channel.drain.remote(cursor[0]), timeout=30)
            except Exception:
                return  # cursor unchanged: nothing lost, retried next drain
            cursor[0] += len(reports)
            for rep in reports:
                if rep["rank"] == 0:
                    if report_hist is not None:
                        # worker-side timestamps: intervals reflect the
                        # loop's real cadence, not drain batching
                        ts = rep.get("ts")
                        if ts is not None and last_report_t[0] is not None:
                            report_hist.observe(1e3 * (ts - last_report_t[0]))
                        if ts is not None:
                            last_report_t[0] = ts
                    history.append(rep["metrics"])
                    last_metrics.clear()
                    last_metrics.update(rep["metrics"])
                    if rep["checkpoint"] is not None:
                        manager.register(rep["checkpoint"], rep["metrics"])

        bc = self._backend_config
        if (
            bc is not None
            and getattr(bc, "enabled", False)
            and n > 1
            and api._cluster() is None
        ):
            raise errors.RayTpuError(
                "JaxDistributedConfig needs process-isolated workers: "
                "jax.distributed.initialize can run once per process, but the "
                "in-process runtime gangs workers as threads. Attach to a "
                "cluster first: ray_tpu.init(address=...)"
            )

        pg = None
        workers = []
        splitters = []
        try:
            if self._scaling.pod_type:
                from ray_tpu.core.accelerators import parse_pod_type, slice_placement_group

                topo = parse_pod_type(self._scaling.pod_type)
                pg = slice_placement_group(self._scaling.pod_type)
                if not pg.ready(timeout=120):
                    raise errors.PlacementGroupUnavailableError(
                        f"slice {self._scaling.pod_type} unavailable"
                    )
                n = topo.num_hosts
            else:
                res = self._scaling.worker_resources()
                bundles = [dict(res) for _ in range(n)]
                pg = api.placement_group(
                    bundles, strategy=self._scaling.placement_strategy, name="train-gang"
                )
                pg.ready(timeout=120)

            channel = _ReportChannel.remote()
            for rank in range(n):
                strategy = api.PlacementGroupSchedulingStrategy(pg, rank)
                res = self._scaling.worker_resources()
                workers.append(
                    _TrainWorker.options(
                        num_cpus=res.get("CPU", 1.0),
                        num_tpus=res.get("TPU", 0.0),
                        resources={k: v for k, v in res.items() if k not in ("CPU", "TPU")},
                        scheduling_strategy=strategy,
                    ).remote(rank, n, trial_dir, channel, self._profile)
                )
            if bc is not None and getattr(bc, "enabled", False):
                # gang-wide SPMD bootstrap: rank 0 elects the coordinator,
                # every member runs jax.distributed.initialize
                coordinator = api.get(
                    workers[0].reserve_coordinator.remote(
                        getattr(bc, "coordinator_port", None)
                    ),
                    timeout=60,
                )
                api.get(
                    [
                        w.setup_distributed.remote(coordinator, n, rank, bc)
                        for rank, w in enumerate(workers)
                    ],
                    timeout=300,
                )
            if resume_ckpt is not None:
                api.get([w.set_resume_checkpoint.remote(resume_ckpt) for w in workers])
            if self._datasets:
                # one shared streaming execution per dataset, split across the
                # gang (reference: dataset.py:1598 streaming_split in Train)
                split_map = {
                    name: ds.streaming_split(n) for name, ds in self._datasets.items()
                }
                splitters = [
                    it.splitter for splits in split_map.values() for it in splits[:1]
                ]
                api.get(
                    [
                        w.set_dataset_shards.remote(
                            {name: splits[rank] for name, splits in split_map.items()}
                        )
                        for rank, w in enumerate(workers)
                    ]
                )

            run_refs = [w.run.remote(self._fn, self._config) for w in workers]

            pending = set(run_refs)
            while pending:
                drain()
                ready, _ = api.wait(list(pending), num_returns=1, timeout=0.1)
                for ref in ready:
                    pending.discard(ref)
                    api.get(ref)  # raises on worker failure
            drain()
            return "ok", None
        except BaseException as e:  # noqa: BLE001
            if channel is not None:
                try:
                    api.get(channel.stop.remote(), timeout=10)
                except Exception:
                    pass
            drain()  # keep reports/checkpoints that landed before the failure
            return "failed", e
        finally:
            for sp in splitters:
                sp.close()  # unwedge the data pump if a worker died mid-stream
            for w in workers:
                try:
                    api.kill(w)
                except Exception:
                    pass
            if channel is not None:
                try:
                    api.kill(channel)
                except Exception:
                    pass
            if pg is not None:
                try:
                    api.remove_placement_group(pg)
                except Exception:
                    pass
