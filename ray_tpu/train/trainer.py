"""JaxTrainer: gang-orchestrated SPMD training.

The DataParallelTrainer equivalent (reference:
python/ray/train/data_parallel_trainer.py:26 DataParallelTrainer →
BackendExecutor _internal/backend_executor.py:69 → WorkerGroup
_internal/worker_group.py:102), built in the Train-v2 controller style
(train/v2/_internal/execution/controller/controller.py:91: a state
machine polling the worker gang, consulting failure policy between
iterations) — with the torch/NCCL bootstrap replaced by the TPU-native
backend: each worker is one host of the gang; `backend_setup` runs
jax.distributed-style bootstrap (on one host: nothing — the mesh IS the
communicator), and in-loop collectives are XLA ops in the user's jitted
step.
"""

from __future__ import annotations

import queue
import threading
import time
import traceback
from typing import Any, Callable, Optional

from ray_tpu.core import api, errors
from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.config import FailureConfig, RunConfig, ScalingConfig
from ray_tpu.train.result import Result
from ray_tpu.train import session as session_mod
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.train")


@api.remote
class _TrainWorker:
    """One gang member (1 per host). Runs the user loop under a session."""

    def __init__(self, rank: int, world_size: int, trial_dir: str, report_queue, stop_event):
        self.ctx = session_mod.TrainContext(
            world_rank=rank,
            world_size=world_size,
            trial_dir=trial_dir,
            report_queue=report_queue,
            stop_event=stop_event,
        )

    def set_resume_checkpoint(self, ckpt) -> bool:
        self.ctx.latest_checkpoint = ckpt
        return True

    def set_dataset_shards(self, shards: dict) -> bool:
        self.ctx.dataset_shards = shards
        return True

    def run(self, fn: Callable, config: dict) -> str:
        session_mod._set_session(self.ctx)
        try:
            fn(config) if _wants_arg(fn) else fn()
            return "done"
        except StopIteration:
            return "stopped"
        finally:
            session_mod._clear_session()


def _wants_arg(fn: Callable) -> bool:
    import inspect

    try:
        return len(inspect.signature(fn).parameters) > 0
    except (TypeError, ValueError):
        return True


class JaxTrainer:
    """Run `train_loop_per_worker` on a gang of workers.

    Inside the loop, user code uses ray_tpu.train.session (report /
    get_checkpoint / get_world_rank) and builds its mesh over the host's
    devices (ray_tpu.parallel.make_mesh). For a pod slice, set
    scaling_config.pod_type and the gang maps 1 worker per slice host via
    the slice placement group.
    """

    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[dict] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[dict] = None,
    ):
        self._fn = train_loop_per_worker
        self._config = train_loop_config or {}
        self._scaling = scaling_config or ScalingConfig()
        self._run_config = run_config or RunConfig()
        self._datasets = datasets or {}

    # -- controller ----------------------------------------------------------

    def fit(self) -> Result:
        trial_dir = self._run_config.resolved_storage_path()
        ckpt_cfg = self._run_config.checkpoint_config
        manager = CheckpointManager(
            trial_dir,
            ckpt_cfg.num_to_keep,
            ckpt_cfg.checkpoint_score_attribute,
            ckpt_cfg.checkpoint_score_order,
        )
        failure_cfg = self._run_config.failure_config
        failures = 0
        resume_ckpt: Optional[Checkpoint] = None
        # metrics/history accumulate ACROSS attempts (a restart continues the
        # same logical run, reference Train-v2 controller semantics)
        history: list[dict] = []
        last_metrics: dict = {}

        while True:
            try:
                outcome, error = self._run_attempt(
                    trial_dir, manager, resume_ckpt, history, last_metrics
                )
            except BaseException as e:  # noqa: BLE001 - setup failure (e.g. infeasible gang)
                outcome, error = "failed", e
            if outcome == "ok":
                return Result(
                    metrics=dict(last_metrics),
                    checkpoint=manager.latest(),
                    path=trial_dir,
                    metrics_history=history,
                )
            failures += 1
            if failure_cfg.max_failures >= 0 and failures > failure_cfg.max_failures:
                return Result(
                    metrics=dict(last_metrics),
                    checkpoint=manager.latest(),
                    path=trial_dir,
                    error=error,
                    metrics_history=history,
                )
            resume_ckpt = manager.latest()
            logger.warning(
                "train attempt failed (%s); restarting gang (failure %d/%s)",
                error, failures, failure_cfg.max_failures,
            )

    def _run_attempt(self, trial_dir, manager, resume_ckpt, history, last_metrics):
        n = self._scaling.num_workers
        report_queue: queue.Queue = queue.Queue()
        stop_event = threading.Event()

        def drain():
            try:
                while True:
                    rep = report_queue.get_nowait()
                    if rep["rank"] == 0:
                        history.append(rep["metrics"])
                        last_metrics.clear()
                        last_metrics.update(rep["metrics"])
                        if rep["checkpoint"] is not None:
                            manager.register(rep["checkpoint"], rep["metrics"])
            except queue.Empty:
                pass

        pg = None
        worker_opts: dict = {"num_cpus": 0}
        if self._scaling.pod_type:
            from ray_tpu.core.accelerators import parse_pod_type, slice_placement_group

            topo = parse_pod_type(self._scaling.pod_type)
            pg = slice_placement_group(self._scaling.pod_type)
            if not pg.ready(timeout=120):
                raise errors.PlacementGroupUnavailableError(
                    f"slice {self._scaling.pod_type} unavailable"
                )
            n = topo.num_hosts
        else:
            res = self._scaling.worker_resources()
            bundles = [dict(res) for _ in range(n)]
            pg = api.placement_group(
                bundles, strategy=self._scaling.placement_strategy, name="train-gang"
            )
            pg.ready(timeout=120)

        workers = []
        splitters = []
        try:
            for rank in range(n):
                strategy = api.PlacementGroupSchedulingStrategy(pg, rank)
                res = self._scaling.worker_resources()
                workers.append(
                    _TrainWorker.options(
                        num_cpus=res.get("CPU", 1.0),
                        num_tpus=res.get("TPU", 0.0),
                        resources={k: v for k, v in res.items() if k not in ("CPU", "TPU")},
                        scheduling_strategy=strategy,
                    ).remote(rank, n, trial_dir, report_queue, stop_event)
                )
            if resume_ckpt is not None:
                api.get([w.set_resume_checkpoint.remote(resume_ckpt) for w in workers])
            if self._datasets:
                # one shared streaming execution per dataset, split across the
                # gang (reference: dataset.py:1598 streaming_split in Train)
                split_map = {
                    name: ds.streaming_split(n) for name, ds in self._datasets.items()
                }
                splitters = [
                    it.splitter for splits in split_map.values() for it in splits[:1]
                ]
                api.get(
                    [
                        w.set_dataset_shards.remote(
                            {name: splits[rank] for name, splits in split_map.items()}
                        )
                        for rank, w in enumerate(workers)
                    ]
                )

            run_refs = [w.run.remote(self._fn, self._config) for w in workers]

            pending = set(run_refs)
            while pending:
                drain()
                ready, _ = api.wait(list(pending), num_returns=1, timeout=0.1)
                for ref in ready:
                    pending.discard(ref)
                    api.get(ref)  # raises on worker failure
            drain()
            return "ok", None
        except BaseException as e:  # noqa: BLE001
            stop_event.set()
            drain()  # keep reports/checkpoints that landed before the failure
            return "failed", e
        finally:
            for sp in splitters:
                sp.close()  # unwedge the data pump if a worker died mid-stream
            for w in workers:
                try:
                    api.kill(w)
                except Exception:
                    pass
            if pg is not None:
                api.remove_placement_group(pg)
