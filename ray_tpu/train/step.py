"""Jitted train-step construction.

One compiled SPMD program per step: forward, backward, optimizer update,
all under a single `jax.jit` with donated state. Gradient reductions,
FSDP all-gathers/reduce-scatters, and TP collectives are inserted by XLA
from the shardings of the inputs — the framework never issues an
explicit allreduce on the training path (contrast reference:
python/ray/train/torch/config.py:115, which bootstraps a NCCL process
group that user code then drives).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax

from ray_tpu.parallel.sharding import ShardingRules, constrain, tree_shardings


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jax.Array

    @classmethod
    def create(cls, params, optimizer: optax.GradientTransformation) -> "TrainState":
        # jit so opt-state shardings propagate from (already-placed) params.
        opt_state = jax.jit(optimizer.init)(params)
        return cls(params=params, opt_state=opt_state, step=jnp.zeros((), jnp.int32))


def init_sharded_params(
    init_fn: Callable[..., Any],
    logical_tree: Any,
    mesh,
    rules: ShardingRules,
    *args,
) -> Any:
    """Run a param initializer with outputs born sharded (no host round-trip)."""
    shardings = tree_shardings(mesh, rules, logical_tree)
    return jax.jit(init_fn, out_shardings=shardings)(*args)


def make_train_step(
    loss_fn: Callable[[Any, Any], jax.Array],
    optimizer: optax.GradientTransformation,
    mesh=None,
    rules: Optional[ShardingRules] = None,
    batch_axes: tuple = ("batch", "seq"),
    grad_accum: int = 1,
    profile: bool = False,
):
    """Build `step(state, batch) -> (state, metrics)` as one jitted program.

    loss_fn(params, batch) -> scalar loss, or (loss, weight) where weight is
    the number of valid tokens the mean was taken over. With grad_accum > 1,
    the batch's leading dim is split into microbatches folded through
    `lax.scan` (keeps the compiled program static; no data-dependent
    Python). Microbatch losses/grads are combined weighted by `weight`, so
    masked batches match the unaccumulated result; scalar-returning loss
    fns get uniform weights (exact only when every microbatch has the same
    number of valid tokens).

    profile=True wraps the jitted step in a ProfiledTrainStep: same
    call signature, plus ``.profile(state, batch)`` which runs the
    ray_tpu.profiler ladder (forward / backward / optimizer-update) and
    returns a roofline-attributed StepProfile, exported to the
    dashboard metrics + timeline surfaces.
    """
    if mesh is not None and rules is None:
        from ray_tpu.parallel.sharding import default_rules

        rules = default_rules()

    def compute_grads(params, batch):
        """Returns (loss, weight, grads); weight=1 for scalar loss fns."""
        from ray_tpu.parallel.context import parallel_context

        if mesh is not None:
            # Ambient (mesh, rules) so mesh-aware ops inside the model —
            # ring attention on `sp`, expert all-to-all on `ep` — can build
            # their shard_maps without signature plumbing.
            with parallel_context(mesh, rules):
                return _compute_grads_inner(params, batch)
        return _compute_grads_inner(params, batch)

    def _compute_grads_inner(params, batch):
        returns_weight = isinstance(
            jax.eval_shape(loss_fn, params, batch), (tuple, list)
        )
        if returns_weight:
            (loss, weight), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, batch
            )
        else:
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            weight = jnp.ones((), jnp.float32)
        return loss, weight, grads

    def step(state: TrainState, batch):
        if mesh is not None:
            batch = jax.tree.map(
                lambda x: constrain(
                    x, mesh, rules, batch_axes[: x.ndim] + (None,) * (x.ndim - len(batch_axes))
                ),
                batch,
            )
        if grad_accum == 1:
            loss, _, grads = compute_grads(state.params, batch)
        else:
            micro = jax.tree.map(
                lambda x: x.reshape((grad_accum, x.shape[0] // grad_accum) + x.shape[1:]),
                batch,
            )

            def accum(carry, mb):
                loss_i, w, g = compute_grads(state.params, mb)
                acc_loss, acc_w, acc_g = carry
                new = (
                    acc_loss + loss_i * w,
                    acc_w + w,
                    jax.tree.map(lambda a, b: a + b * w, acc_g, g),
                )
                return new, None

            zero = (
                jnp.zeros((), jnp.float32),
                jnp.zeros((), jnp.float32),
                jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), state.params),
            )
            (loss_sum, w_sum, grad_sum), _ = jax.lax.scan(accum, zero, micro)
            loss = loss_sum / w_sum
            grads = jax.tree.map(lambda g: g / w_sum, grad_sum)

        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        grad_norm = optax.global_norm(grads)
        new_state = TrainState(params=params, opt_state=opt_state, step=state.step + 1)
        return new_state, {"loss": loss, "grad_norm": grad_norm}

    jitted = jax.jit(step, donate_argnums=(0,))
    if profile:
        return ProfiledTrainStep(jitted, step, loss_fn, optimizer, grad_accum)
    return jitted


class ProfiledTrainStep:
    """A jitted train step plus its measurement hook.

    Calls pass straight through to the compiled program (no per-step
    fencing — a fence would bill the device tunnel's round trip to every
    step). ``profile()`` runs the subsystem's chained-probe ladder on
    the SAME loss/optimizer and publishes the StepProfile to the metrics
    registry and timeline buffer.
    """

    def __init__(self, jitted, step_body, loss_fn, optimizer, grad_accum=1):
        self._jitted = jitted
        self._step_body = step_body
        self._loss_fn = loss_fn
        self._optimizer = optimizer
        self._grad_accum = grad_accum
        self.last_profile = None

    def __call__(self, state, batch):
        return self._jitted(state, batch)

    def profile(
        self,
        state: TrainState,
        batch,
        *,
        iters: int = 6,
        warmup: int = 2,
        export_observability: bool = True,
    ):
        """Roofline-attributed StepProfile of this step on (state, batch).

        Uses the generic forward/backward/optimizer ladder (works for
        any loss_fn); for the finer llama decomposition use
        ray_tpu.profiler.profile_train_step directly."""
        from ray_tpu.profiler import StepProfile, profile_segments
        from ray_tpu.profiler.segments import generic_train_segments

        parts, whole_fn = generic_train_segments(
            self._loss_fn, self._optimizer, state, batch,
            step_body=self._step_body, iters=iters, warmup=warmup,
        )
        segments = profile_segments(parts, iters=iters, warmup=warmup)
        prof = StepProfile.build(
            "train_step", segments, whole_fn(),
            meta={"ladder": "generic", "grad_accum": self._grad_accum},
        )
        if export_observability:
            from ray_tpu.profiler import export

            export(prof)
        self.last_profile = prof
        return prof
