"""Train-tier config dataclasses (analog of reference ray.air.config:
ScalingConfig air/config.py:102, FailureConfig :397, CheckpointConfig
:447, RunConfig :596)."""

from __future__ import annotations

import dataclasses
import os
import tempfile
from typing import Optional


@dataclasses.dataclass
class ScalingConfig:
    """How many workers and what each one owns.

    TPU-first reading: `num_workers` is the number of HOST processes in the
    gang (1 per TPU host); `chips_per_worker` pins that host's chips; the
    in-host parallelism (all 4/8 chips) is expressed by the worker's mesh,
    not by more workers.
    """

    num_workers: int = 1
    use_tpu: bool = False
    chips_per_worker: int = 0
    resources_per_worker: dict = dataclasses.field(default_factory=dict)
    placement_strategy: str = "PACK"
    pod_type: Optional[str] = None  # e.g. "v5p-16": gang = the slice's hosts
    # elastic training (reference: Train v2 scaling_policy): when set, each
    # attempt sizes the gang to what the cluster can actually place, between
    # min_workers and num_workers — a shrunk cluster trains on fewer hosts
    # instead of failing; a recovered one scales back up on the next attempt
    min_workers: Optional[int] = None

    def worker_resources(self) -> dict:
        res = dict(self.resources_per_worker)
        if self.use_tpu and self.chips_per_worker:
            res["TPU"] = float(self.chips_per_worker)
        res.setdefault("CPU", 1.0)
        return res


@dataclasses.dataclass
class FailureConfig:
    max_failures: int = 0  # worker-group restarts allowed; -1 = unlimited


@dataclasses.dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None  # None = keep all
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"


@dataclasses.dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = dataclasses.field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = dataclasses.field(default_factory=CheckpointConfig)

    def resolved_storage_path(self) -> str:
        base = self.storage_path or os.path.join(tempfile.gettempdir(), "ray_tpu_results")
        name = self.name or "train_run"
        return os.path.join(base, name)
