"""In-worker training session (analog of reference
python/ray/train/_internal/session.py: report:405, get_context).

Worker code calls `session.report(metrics, checkpoint=...)`; the
controller consumes reports between polls. Thread-local so concurrent
trainer workers in one host process don't collide.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
from typing import Any, Optional

from ray_tpu.train.checkpoint import Checkpoint

_local = threading.local()


@dataclasses.dataclass
class TrainContext:
    world_rank: int
    world_size: int
    trial_dir: str
    report_queue: Any  # queue.Queue shared with the controller
    latest_checkpoint: Optional[Checkpoint] = None
    group_name: str = "train"
    stop_event: Optional[threading.Event] = None
    dataset_shards: dict = dataclasses.field(default_factory=dict)
    # set by JaxTrainer(profile=True): user loops check
    # session.profiling_enabled() to turn on make_train_step(profile=...)
    profile: bool = False


def _set_session(ctx: TrainContext) -> None:
    _local.ctx = ctx


def _clear_session() -> None:
    _local.ctx = None


def get_context() -> TrainContext:
    ctx = getattr(_local, "ctx", None)
    if ctx is None:
        raise RuntimeError("not inside a train worker (no active session)")
    return ctx


def get_world_rank() -> int:
    return get_context().world_rank


def get_world_size() -> int:
    return get_context().world_size


def get_trial_dir() -> str:
    return get_context().trial_dir


def get_checkpoint() -> Optional[Checkpoint]:
    """Checkpoint to resume from (set after a failure restart)."""
    return get_context().latest_checkpoint


def profiling_enabled() -> bool:
    """True when the driving JaxTrainer was built with profile=True —
    the worker-side signal to build its step via
    make_train_step(..., profile=True) and publish a StepProfile."""
    return bool(get_context().profile)


def get_dataset_shard(name: str = "train"):
    """This worker's split of a Dataset passed to JaxTrainer(datasets=...)
    (reference: ray.train.get_dataset_shard backed by streaming_split).
    One streaming pass per attempt; re-create the trainer run for epochs
    beyond the pipeline's output."""
    shards = get_context().dataset_shards
    if name not in shards:
        raise KeyError(
            f"no dataset shard {name!r}; pass datasets={{'{name}': ds}} to the trainer"
        )
    return shards[name]


def report(metrics: dict, checkpoint: Optional[Checkpoint] = None) -> None:
    ctx = get_context()
    import time

    ctx.report_queue.put(
        {"rank": ctx.world_rank, "metrics": dict(metrics),
         "checkpoint": checkpoint, "ts": time.time()}
    )
    if ctx.stop_event is not None and ctx.stop_event.is_set():
        raise StopIteration("controller requested stop")
