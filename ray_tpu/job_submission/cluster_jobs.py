"""Cluster-backed job submission: drivers run ON the cluster.

Reference analog: python/ray/dashboard/modules/job/job_manager.py — the
job server packages the submission's working_dir, schedules a
supervisor on some node, and tracks JobStatus/logs in the GCS so ANY
client can query them. Here:

  * the entrypoint runs as a cluster TASK (max_retries=0 — a driver
    must not silently re-run) whose runtime_env carries the packaged
    working_dir (content-addressed staging via the object plane,
    cluster/runtime_env.py) and env_vars;
  * the runner supervises the entrypoint subprocess from inside the
    worker, flushing status + log tail to the GCS KV (ns "jobs") every
    second, and polls a stop flag so stop_job() works cross-process;
  * the client is stateless beyond its GCS connection: status, logs and
    listing come from the KV, so a second client on another machine
    sees the same jobs (the reference's HTTP-client property).
"""

from __future__ import annotations

import json
import os
import subprocess
import time
import uuid
from typing import Optional

from ray_tpu.job_submission import JobInfo, JobStatus
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.jobs.cluster")

_NS = "jobs"
_LOG_TAIL = 1 << 20  # KV carries the last 1MB of driver output


def _kv_key(sid: str, kind: str) -> bytes:
    return f"{kind}/{sid}".encode()


def _job_runner(sid: str, entrypoint: str, env_vars: dict) -> str:
    """Runs on a cluster worker: supervise the entrypoint subprocess,
    stream status/logs to the GCS KV, honor the stop flag."""
    import threading

    from ray_tpu.cluster.client import _ambient_client

    client = _ambient_client()

    def put(kind: str, value: dict) -> None:
        client.kv_put(_kv_key(sid, kind), json.dumps(value).encode(), ns=_NS)

    import signal

    env = dict(os.environ)
    env.update({str(k): str(v) for k, v in env_vars.items()})
    env["RAY_TPU_JOB_ID"] = sid
    log_path = os.path.join(
        os.environ.get("TMPDIR", "/tmp"), f"ray_tpu-job-{sid}.log"
    )
    start = time.time()
    # a stop raised while we were still QUEUED (client stop_job, or the
    # PENDING-staleness failover that already recorded FAILED): exit
    # without running — putting RUNNING here would flip a terminal status
    if client.kv_get(_kv_key(sid, "stop"), ns=_NS) is not None:
        raw = client.kv_get(_kv_key(sid, "status"), ns=_NS)
        doc = json.loads(bytes(raw).decode()) if raw is not None else {}
        if doc.get("status") not in JobStatus.TERMINAL:
            put("status", {"status": JobStatus.STOPPED,
                           "start_time": doc.get("start_time", start),
                           "end_time": time.time(),
                           "message": "stopped before start"})
        return JobStatus.STOPPED
    put("status", {"status": JobStatus.RUNNING, "start_time": start,
                   "node": os.environ.get("RAY_TPU_NODE_ID", "?")})
    with open(log_path, "wb") as logf:
        # own process GROUP: stop must reach the shell's descendants,
        # not just /bin/sh (a `a.py && b.py` entrypoint would orphan
        # the python driver otherwise)
        proc = subprocess.Popen(
            entrypoint, shell=True, cwd=os.getcwd(),  # working_dir cwd
            env=env, stdout=logf, stderr=subprocess.STDOUT,
            start_new_session=True,
        )

        def killpg(sig):
            try:
                os.killpg(os.getpgid(proc.pid), sig)
            except (ProcessLookupError, PermissionError, OSError):
                pass

        stop = threading.Event()

        def watch():
            pushed = -1
            while not stop.wait(2.0):
                try:
                    # liveness heartbeat: clients infer a dead driver
                    # (node loss) from staleness, independent of the
                    # submitting process surviving
                    client.kv_put(
                        _kv_key(sid, "hb"), str(time.time()).encode(), ns=_NS
                    )
                    size = os.path.getsize(log_path)
                    if size != pushed:  # skip identical re-pushes
                        with open(log_path, "rb") as f:
                            f.seek(max(0, size - _LOG_TAIL))
                            client.kv_put(
                                _kv_key(sid, "logs"), f.read(), ns=_NS
                            )
                        pushed = size
                    if client.kv_get(_kv_key(sid, "stop"), ns=_NS) is not None:
                        killpg(signal.SIGTERM)
                        time.sleep(3)
                        if proc.poll() is None:
                            killpg(signal.SIGKILL)
                        return
                except Exception:  # noqa: BLE001 — KV hiccup: keep going
                    pass

        t = threading.Thread(target=watch, daemon=True)
        t.start()
        rc = proc.wait()
        stop.set()
        t.join(timeout=5)
    with open(log_path, "rb") as f:
        f.seek(max(0, os.path.getsize(log_path) - _LOG_TAIL))
        client.kv_put(_kv_key(sid, "logs"), f.read(), ns=_NS)
    stopped = client.kv_get(_kv_key(sid, "stop"), ns=_NS) is not None
    status = (
        JobStatus.STOPPED if stopped
        else JobStatus.SUCCEEDED if rc == 0 else JobStatus.FAILED
    )
    put("status", {"status": status, "start_time": start,
                   "end_time": time.time(),
                   "message": "" if rc == 0 else f"exit code {rc}"})
    try:
        os.unlink(log_path)
    except OSError:
        pass
    return status


class ClusterJobSubmissionClient:
    """Submit driver scripts to a running cluster (``init(address=...)``
    form of the reference JobSubmissionClient)."""

    def __init__(self, address: str):
        from ray_tpu.core import api

        ambient = api._cluster()
        if ambient is not None and ambient.address == address:
            self._backend = ambient
        else:
            # a dedicated backend: reusing an ambient attachment to a
            # DIFFERENT cluster would silently submit to the wrong one
            from ray_tpu.core.cluster_backend import ClusterBackend

            self._backend = ClusterBackend(address)
        self._client = self._backend.client

    def submit_job(
        self,
        *,
        entrypoint: str,
        submission_id: Optional[str] = None,
        runtime_env: Optional[dict] = None,
        metadata: Optional[dict] = None,
        resources: Optional[dict] = None,
    ) -> str:
        import threading

        sid = submission_id or f"raytpu-job-{uuid.uuid4().hex[:10]}"
        # ATOMIC claim of the id (kv set-if-absent): two clients with the
        # same explicit submission_id must not both launch drivers
        claimed = self._client.gcs.call("kv_put", {
            "ns": _NS, "key": _kv_key(sid, "spec"), "nx": True,
            "value": json.dumps({
                "entrypoint": entrypoint,
                "metadata": metadata or {},
                "submit_time": time.time(),
            }).encode(),
        })
        if not claimed.get("ok"):
            raise ValueError(f"job {sid!r} already exists")
        renv = dict(runtime_env or {})
        env_vars = dict(renv.pop("env_vars", {}))
        self._client.kv_put(
            _kv_key(sid, "status"),
            json.dumps({"status": JobStatus.PENDING,
                        "start_time": time.time()}).encode(),
            ns=_NS,
        )
        # the driver task: max_retries=0 (drivers must not re-run), the
        # packaged working_dir travels through the runtime_env store
        ref = self._client.submit(
            _job_runner,
            (sid, entrypoint, env_vars),
            resources=resources or {"num_cpus": 1},
            max_retries=0,
            runtime_env=renv or None,
            desc=f"job:{sid}",
        )

        def reconcile():
            # the runner's own status puts cover the happy path; this
            # covers the task DYING (worker/node death, crash before the
            # first put) — otherwise the KV would read PENDING forever
            try:
                self._client.get(ref, timeout=30 * 24 * 3600)
            except Exception as e:  # noqa: BLE001 — task-level failure
                try:
                    doc = self._status_doc(sid)
                except Exception:  # noqa: BLE001
                    doc = {}
                if doc.get("status") not in JobStatus.TERMINAL:
                    self._client.kv_put(
                        _kv_key(sid, "status"),
                        json.dumps({
                            "status": JobStatus.FAILED,
                            "start_time": doc.get("start_time", time.time()),
                            "end_time": time.time(),
                            "message": f"driver task died: {e!r}"[:500],
                        }).encode(),
                        ns=_NS,
                    )

        threading.Thread(
            target=reconcile, name=f"job-reconcile-{sid}", daemon=True
        ).start()
        return sid

    # -- queries (KV-backed: any client sees the same state) ------------------

    HEARTBEAT_STALE_S = 30.0
    # generous: covers queueing + runtime_env staging + worker spawn on a
    # loaded cluster before the runner's first status/heartbeat put
    PENDING_STALE_S = 300.0

    def _status_doc(self, sid: str) -> dict:
        raw = self._client.kv_get(_kv_key(sid, "status"), ns=_NS)
        if raw is None:
            raise ValueError(f"unknown job {sid!r}")
        doc = json.loads(bytes(raw).decode())
        if doc.get("status") == JobStatus.RUNNING:
            # a RUNNING job whose runner heartbeat went stale died with
            # its worker/node — ANY client can detect and record it
            # (the submitter's task-ref watcher may itself be gone)
            hb = self._client.kv_get(_kv_key(sid, "hb"), ns=_NS)
            if hb is not None:
                age = time.time() - float(bytes(hb).decode())
                if age > self.HEARTBEAT_STALE_S:
                    doc = {**doc, "status": JobStatus.FAILED,
                           "end_time": time.time(),
                           "message": f"driver heartbeat stale ({age:.0f}s)"}
                    self._client.kv_put(
                        _kv_key(sid, "status"),
                        json.dumps(doc).encode(), ns=_NS,
                    )
        elif doc.get("status") == JobStatus.PENDING:
            # a PENDING job whose runner never heartbeat at all died
            # before its first put (submitter crashed pre-reconcile, or
            # the driver task was lost with its node): without this, the
            # KV reads PENDING forever for every other client
            hb = self._client.kv_get(_kv_key(sid, "hb"), ns=_NS)
            if hb is None:
                spec_raw = self._client.kv_get(_kv_key(sid, "spec"), ns=_NS)
                submitted = None
                if spec_raw is not None:
                    try:
                        submitted = json.loads(
                            bytes(spec_raw).decode()
                        ).get("submit_time")
                    except (ValueError, AttributeError):
                        submitted = None
                if submitted is None:
                    submitted = doc.get("start_time")
                age = time.time() - submitted if submitted else 0.0
                if age > self.PENDING_STALE_S:
                    doc = {**doc, "status": JobStatus.FAILED,
                           "end_time": time.time(),
                           "message": (
                               f"job pending with no driver heartbeat for "
                               f"{age:.0f}s (driver task lost before start)"
                           )}
                    # also raise the stop flag: if the driver task was
                    # merely QUEUED (not lost) and gets a slot later, the
                    # runner's stop check kills it immediately instead of
                    # re-running a job every client already saw FAILED
                    self._client.kv_put(_kv_key(sid, "stop"), b"1", ns=_NS)
                    self._client.kv_put(
                        _kv_key(sid, "status"),
                        json.dumps(doc).encode(), ns=_NS,
                    )
        return doc

    def get_job_status(self, submission_id: str) -> str:
        return self._status_doc(submission_id)["status"]

    def get_job_info(self, submission_id: str) -> JobInfo:
        doc = self._status_doc(submission_id)
        raw = self._client.kv_get(_kv_key(submission_id, "spec"), ns=_NS)
        spec = json.loads(bytes(raw).decode()) if raw else {}
        return JobInfo(
            submission_id=submission_id,
            entrypoint=spec.get("entrypoint", ""),
            status=doc["status"],
            message=doc.get("message", ""),
            start_time=doc.get("start_time", 0.0),
            end_time=doc.get("end_time"),
            metadata=spec.get("metadata", {}),
        )

    def get_job_logs(self, submission_id: str) -> str:
        raw = self._client.kv_get(_kv_key(submission_id, "logs"), ns=_NS)
        return "" if raw is None else bytes(raw).decode(errors="replace")

    def list_jobs(self) -> list[JobInfo]:
        sids = [
            bytes(k).decode().split("/", 1)[1]
            for k in self._client.gcs.call("kv_keys", {"ns": _NS}) or ()
            if bytes(k).decode().startswith("spec/")
        ]
        return [self.get_job_info(s) for s in sorted(sids)]

    def stop_job(self, submission_id: str) -> bool:
        if self.get_job_status(submission_id) in JobStatus.TERMINAL:
            return False
        self._client.kv_put(_kv_key(submission_id, "stop"), b"1", ns=_NS)
        return True

    def wait_until_finish(
        self, submission_id: str, timeout: float = 120.0, poll_s: float = 0.25
    ) -> str:
        deadline = time.time() + timeout
        while time.time() < deadline:
            st = self.get_job_status(submission_id)
            if st in JobStatus.TERMINAL:
                return st
            time.sleep(poll_s)
        raise TimeoutError(f"job {submission_id} still running after {timeout}s")
