"""Job submission: run driver scripts as supervised subprocesses.

Reference analog: python/ray/job_submission/ (JobSubmissionClient) +
python/ray/dashboard/modules/job/ (job_manager.py spawns a supervisor
per job, captures logs, tracks JobStatus). Single-host: a supervisor
thread per job; entrypoints are shell commands; runtime_env supports
env_vars and working_dir (the subset that matters without a cluster
package store).
"""

from __future__ import annotations

import os
import subprocess
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Optional

from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.jobs")


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    TERMINAL = (SUCCEEDED, FAILED, STOPPED)


@dataclass
class JobInfo:
    submission_id: str
    entrypoint: str
    status: str = JobStatus.PENDING
    message: str = ""
    start_time: float = field(default_factory=time.time)
    end_time: Optional[float] = None
    metadata: dict = field(default_factory=dict)
    log_path: str = ""


class JobSubmissionClient:
    """Local job manager (the reference's client talks HTTP to the
    dashboard job server; the manager semantics are what matters here)."""

    def __init__(self, address: Optional[str] = None, log_dir: Optional[str] = None):
        self._jobs: dict[str, JobInfo] = {}
        self._procs: dict[str, subprocess.Popen] = {}
        self._lock = threading.Lock()
        self._log_dir = log_dir or os.path.join(
            os.environ.get("TMPDIR", "/tmp"), "ray_tpu_jobs"
        )
        os.makedirs(self._log_dir, exist_ok=True)

    def submit_job(
        self,
        *,
        entrypoint: str,
        submission_id: Optional[str] = None,
        runtime_env: Optional[dict] = None,
        metadata: Optional[dict] = None,
    ) -> str:
        sid = submission_id or f"raytpu-job-{uuid.uuid4().hex[:10]}"
        with self._lock:
            if sid in self._jobs:
                raise ValueError(f"job {sid!r} already exists")
            info = JobInfo(
                submission_id=sid,
                entrypoint=entrypoint,
                metadata=metadata or {},
                log_path=os.path.join(self._log_dir, f"{sid}.log"),
            )
            self._jobs[sid] = info

        env = dict(os.environ)
        renv = runtime_env or {}
        env.update({str(k): str(v) for k, v in renv.get("env_vars", {}).items()})
        env["RAY_TPU_JOB_ID"] = sid
        # jobs must always be able to import the framework, wherever their
        # entrypoint script lives (the reference relies on ray being
        # pip-installed; the equivalent here is PYTHONPATH injection)
        from ray_tpu.utils.env import inject_framework_pythonpath

        inject_framework_pythonpath(env)
        cwd = renv.get("working_dir") or os.getcwd()

        def supervise():
            try:
                with open(info.log_path, "wb") as logf:
                    proc = subprocess.Popen(
                        entrypoint,
                        shell=True,
                        cwd=cwd,
                        env=env,
                        stdout=logf,
                        stderr=subprocess.STDOUT,
                    )
                    with self._lock:
                        self._procs[sid] = proc
                        # stop_job may have landed before Popen: honor it
                        stopped_early = info.status == JobStatus.STOPPED
                        if not stopped_early:
                            info.status = JobStatus.RUNNING
                    if stopped_early:
                        proc.terminate()
                    rc = proc.wait()
                with self._lock:
                    self._procs.pop(sid, None)
                    info.end_time = time.time()
                    if info.status == JobStatus.STOPPED:
                        pass  # stop_job already set it
                    elif rc == 0:
                        info.status = JobStatus.SUCCEEDED
                    else:
                        info.status = JobStatus.FAILED
                        info.message = f"exit code {rc}"
            except Exception as e:
                with self._lock:
                    info.status = JobStatus.FAILED
                    info.message = repr(e)
                    info.end_time = time.time()

        threading.Thread(target=supervise, name=f"job-{sid}", daemon=True).start()
        return sid

    def get_job_status(self, submission_id: str) -> str:
        return self._info(submission_id).status

    def get_job_info(self, submission_id: str) -> JobInfo:
        return self._info(submission_id)

    def get_job_logs(self, submission_id: str) -> str:
        info = self._info(submission_id)
        if not os.path.exists(info.log_path):
            return ""
        with open(info.log_path, errors="replace") as f:
            return f.read()

    def list_jobs(self) -> list[JobInfo]:
        with self._lock:
            return list(self._jobs.values())

    def stop_job(self, submission_id: str) -> bool:
        with self._lock:
            info = self._jobs.get(submission_id)
            proc = self._procs.get(submission_id)
            if info is None:
                raise ValueError(f"unknown job {submission_id!r}")
            if info.status in JobStatus.TERMINAL:
                return False
            info.status = JobStatus.STOPPED
            info.message = "stopped by user"
        if proc is not None:
            try:
                proc.terminate()
                try:
                    proc.wait(timeout=3)
                except subprocess.TimeoutExpired:
                    proc.kill()
            except Exception:
                pass
        return True

    def delete_job(self, submission_id: str) -> bool:
        with self._lock:
            info = self._jobs.get(submission_id)
            if info is None or info.status not in JobStatus.TERMINAL:
                return False
            del self._jobs[submission_id]
        try:
            os.unlink(info.log_path)
        except OSError:
            pass
        return True

    def wait_until_finish(
        self, submission_id: str, timeout: float = 60.0, poll_s: float = 0.1
    ) -> str:
        deadline = time.time() + timeout
        while time.time() < deadline:
            st = self.get_job_status(submission_id)
            if st in JobStatus.TERMINAL:
                return st
            time.sleep(poll_s)
        raise TimeoutError(f"job {submission_id} still running after {timeout}s")

    def _info(self, sid: str) -> JobInfo:
        with self._lock:
            info = self._jobs.get(sid)
        if info is None:
            raise ValueError(f"unknown job {sid!r}")
        return info


def __getattr__(name):
    # cluster-backed client lives in its own module (imports the cluster
    # plane; the local manager must stay import-light)
    if name == "ClusterJobSubmissionClient":
        from ray_tpu.job_submission.cluster_jobs import ClusterJobSubmissionClient

        return ClusterJobSubmissionClient
    raise AttributeError(name)


__all__ = [
    "ClusterJobSubmissionClient", "JobInfo", "JobStatus", "JobSubmissionClient",
]
