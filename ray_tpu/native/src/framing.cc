// Native frame I/O for the RPC plane (ray_tpu/cluster/rpc.py).
//
// Reference analog: the gRPC/C++ transport under src/ray/rpc/ — here the
// wire format stays the framework's length-prefixed frames, but the
// receive hot loop (read 4-byte length, then exactly `len` payload
// bytes) runs in C with the GIL released: no Python-level recv loop, no
// bytes concatenation, one malloc per frame. Enabled from Python with
// RAY_TPU_NATIVE_FRAMING=1 (see rpc.py RpcClient._read_loop); the
// single-core profile (benchmarks/PROFILE_taskplane_r05.md) shows the
// dominant cost is elsewhere, so this stays opt-in.

#include <arpa/inet.h>
#include <cerrno>
#include <cstdlib>
#include <cstring>
#include <sys/socket.h>
#include <poll.h>
#include <sys/uio.h>
#include <unistd.h>

namespace {

// Read exactly n bytes; returns 0 on success, -1 on EOF/error.
int read_exact(int fd, unsigned char* buf, size_t n) {
  size_t got = 0;
  while (got < n) {
    ssize_t r = recv(fd, buf + got, n - got, 0);
    if (r == 0) return -1;  // orderly EOF
    if (r < 0) {
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        struct pollfd pfd = {fd, POLLIN, 0};
        if (poll(&pfd, 1, -1) < 0 && errno != EINTR) return -1;
        continue;
      }
      return -1;
    }
    got += static_cast<size_t>(r);
  }
  return 0;
}

}  // namespace

extern "C" {

// Read one frame. On success returns the payload length (>= 0) and sets
// *out to a malloc'd buffer the caller releases with frame_free. Returns
// -1 on EOF / connection error, -2 on allocation failure / oversized
// frame (> 2^31, matching rpc.py MAX_FRAME).
long frame_read(int fd, unsigned char** out) {
  unsigned char hdr[4];
  if (read_exact(fd, hdr, 4) != 0) return -1;
  uint32_t len = ntohl(*reinterpret_cast<uint32_t*>(hdr));
  if (len > (1u << 31)) return -2;
  unsigned char* buf = static_cast<unsigned char*>(malloc(len ? len : 1));
  if (buf == nullptr) return -2;
  if (read_exact(fd, buf, len) != 0) {
    free(buf);
    return -1;
  }
  *out = buf;
  return static_cast<long>(len);
}

void frame_free(unsigned char* p) { free(p); }

// Write header + payload with one writev (no Python-side concat copy).
// Returns 0 on success, -1 on connection error, -2 on oversized frame
// (> 2^31, matching the read-side / Python MAX_FRAME bound — silent
// 32-bit truncation would desync the peer's frame parser).
// EAGAIN/EWOULDBLOCK (the fd may carry a non-blocking/timeout mode from
// Python's settimeout) waits for writability instead of failing with a
// partial frame on the wire.
int frame_write(int fd, const unsigned char* data, unsigned long len) {
  if (len > (1ul << 31)) return -2;
  unsigned char hdr[4];
  *reinterpret_cast<uint32_t*>(hdr) = htonl(static_cast<uint32_t>(len));
  struct iovec iov[2];
  size_t total = 4 + len;
  size_t sent = 0;
  while (sent < total) {
    ssize_t r;
    if (sent < 4) {
      iov[0].iov_base = hdr + sent;
      iov[0].iov_len = 4 - sent;
      iov[1].iov_base = const_cast<unsigned char*>(data);
      iov[1].iov_len = len;
      r = writev(fd, iov, 2);
    } else {
      r = send(fd, data + (sent - 4), total - sent, 0);
    }
    if (r < 0) {
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        struct pollfd pfd = {fd, POLLOUT, 0};
        if (poll(&pfd, 1, -1) < 0 && errno != EINTR) return -1;
        continue;
      }
      return -1;
    }
    sent += static_cast<size_t>(r);
  }
  return 0;
}

}  // extern "C"
