// Native frame I/O for the RPC plane (ray_tpu/cluster/rpc.py).
//
// Reference analog: the gRPC/C++ transport under src/ray/rpc/ — here the
// wire format stays the framework's length-prefixed frames, but the
// receive hot loop (read 4-byte length, then exactly `len` payload
// bytes) runs in C with the GIL released: no Python-level recv loop, no
// bytes concatenation, one malloc per frame. Enabled from Python with
// RAY_TPU_NATIVE_FRAMING=1 (see rpc.py RpcClient._read_loop); the
// single-core profile (benchmarks/PROFILE_taskplane_r05.md) shows the
// dominant cost is elsewhere, so this stays opt-in.
//
// All waits are BOUNDED polls (timeout_ms; <0 = wait forever). The
// previous poll(-1) meant a peer that stalled mid-frame wedged the
// caller for good — and frame_write runs under RpcClient._wlock, so one
// stalled peer froze every thread that touches that connection.

#include <arpa/inet.h>
#include <cerrno>
#include <cstdlib>
#include <cstring>
#include <sys/socket.h>
#include <poll.h>
#include <sys/uio.h>
#include <unistd.h>

namespace {

// Wait for fd readiness. Returns 0 ready, 1 timed out, -1 error.
// timeout_ms < 0 waits forever (legacy behavior).
int wait_fd(int fd, short events, int timeout_ms) {
  struct pollfd pfd = {fd, events, 0};
  for (;;) {
    int r = poll(&pfd, 1, timeout_ms);
    if (r > 0) return 0;
    if (r == 0) return 1;  // expired
    if (errno == EINTR) continue;  // retry with the full bound: simple,
                                   // and signals here are rare
    return -1;
  }
}

// Read exactly n bytes; *consumed reports progress so the caller can
// distinguish "idle, nothing arrived" from "stalled mid-frame".
// Returns 0 on success, -1 on EOF/error, 1 on poll timeout.
//
// recv always uses MSG_DONTWAIT: the fds rpc.py hands over are usually
// BLOCKING sockets (settimeout(None)), and a blocking recv would park
// inside the kernel where no timeout can reach it. Readiness waiting is
// poll()'s job here, with the caller's bound.
int read_exact(int fd, unsigned char* buf, size_t n, int timeout_ms,
               size_t* consumed) {
  size_t got = 0;
  while (got < n) {
    ssize_t r = recv(fd, buf + got, n - got, MSG_DONTWAIT);
    if (r == 0) {
      *consumed = got;
      return -1;  // orderly EOF
    }
    if (r < 0) {
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        int w = wait_fd(fd, POLLIN, timeout_ms);
        if (w == 0) continue;
        *consumed = got;
        return w;  // 1 = timeout, -1 = poll error
      }
      *consumed = got;
      return -1;
    }
    got += static_cast<size_t>(r);
  }
  *consumed = got;
  return 0;
}

}  // namespace

extern "C" {

// Read one frame, waiting at most timeout_ms at each blocking point
// (<0 = forever). On success returns the payload length (>= 0) and sets
// *out to a malloc'd buffer the caller releases with frame_free.
// Returns -1 on EOF / connection error / a MID-FRAME stall past the
// bound (the peer wedged with half a frame on the wire: the connection
// is unrecoverable — resyncing the length-prefixed stream is not
// possible), -2 on allocation failure / oversized frame (> 2^31,
// matching rpc.py MAX_FRAME), -3 on an IDLE timeout (no header byte
// arrived: nothing consumed, safe to retry — the Python loop uses this
// to re-check its shutdown flag).
long frame_read(int fd, unsigned char** out, int timeout_ms) {
  unsigned char hdr[4];
  size_t consumed = 0;
  int rc = read_exact(fd, hdr, 4, timeout_ms, &consumed);
  if (rc == 1) return consumed == 0 ? -3 : -1;
  if (rc != 0) return -1;
  uint32_t len = ntohl(*reinterpret_cast<uint32_t*>(hdr));
  if (len > (1u << 31)) return -2;
  unsigned char* buf = static_cast<unsigned char*>(malloc(len ? len : 1));
  if (buf == nullptr) return -2;
  rc = read_exact(fd, buf, len, timeout_ms, &consumed);
  if (rc != 0) {  // mid-frame timeout or error: either way the stream
    free(buf);    // is desynced — surface a connection error
    return -1;
  }
  *out = buf;
  return static_cast<long>(len);
}

void frame_free(unsigned char* p) { free(p); }

// Write header + payload with one writev (no Python-side concat copy).
// Returns 0 on success, -1 on connection error OR a stalled peer
// (socket unwritable for timeout_ms; <0 waits forever), -2 on
// oversized frame (> 2^31, matching the read-side / Python MAX_FRAME
// bound — silent 32-bit truncation would desync the peer's frame
// parser). A timeout mid-write leaves a partial frame on the wire;
// the caller must treat the connection as dead (rpc.py does: OSError
// -> RpcError -> reconnect), never retry the same frame.
int frame_write(int fd, const unsigned char* data, unsigned long len,
                int timeout_ms) {
  if (len > (1ul << 31)) return -2;
  unsigned char hdr[4];
  *reinterpret_cast<uint32_t*>(hdr) = htonl(static_cast<uint32_t>(len));
  struct iovec iov[2];
  size_t total = 4 + len;
  size_t sent = 0;
  while (sent < total) {
    // MSG_DONTWAIT everywhere (see read_exact): a blocking fd must not
    // park the writer in the kernel beyond the poll bound
    ssize_t r;
    if (sent < 4) {
      iov[0].iov_base = hdr + sent;
      iov[0].iov_len = 4 - sent;
      iov[1].iov_base = const_cast<unsigned char*>(data);
      iov[1].iov_len = len;
      struct msghdr msg = {};
      msg.msg_iov = iov;
      msg.msg_iovlen = 2;
      r = sendmsg(fd, &msg, MSG_DONTWAIT);
    } else {
      r = send(fd, data + (sent - 4), total - sent, MSG_DONTWAIT);
    }
    if (r < 0) {
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        if (wait_fd(fd, POLLOUT, timeout_ms) == 0) continue;
        return -1;  // stalled peer or poll error: connection is dead
      }
      return -1;
    }
    sent += static_cast<size_t>(r);
  }
  return 0;
}

}  // extern "C"
