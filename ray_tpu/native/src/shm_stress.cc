// Sanitizer stress driver for the shared-memory object store.
//
// Role analog: the reference gates its C++ object-store core under
// ASAN/TSAN CI jobs (src/ray/object_manager tests run under
// sanitizers). This binary exercises the same store C ABI from many
// threads so `make asan` / `make tsan` can prove the allocator and
// slot table are clean under the respective sanitizer.
//
// Exit code 0 = no sanitizer report (sanitizers abort non-zero).

#include <unistd.h>

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void* shm_store_create(const char* path, uint64_t capacity);
void* shm_store_open(const char* path);
void shm_store_close(void* store);
uint64_t shm_create(void* store, const uint8_t* id, uint64_t size);
int shm_seal(void* store, const uint8_t* id);
uint64_t shm_get(void* store, const uint8_t* id, uint64_t* size_out);
int shm_release(void* store, const uint8_t* id);
int shm_delete(void* store, const uint8_t* id);
int shm_contains(void* store, const uint8_t* id);
uint8_t* shm_base(void* store);
void shm_stats(void* store, uint64_t* capacity, uint64_t* used,
               uint64_t* num_objects, uint64_t* num_evictions);
}

static void make_id(uint8_t* id, int tid, int k) {
  std::memset(id, 0, 16);
  std::memcpy(id, &tid, sizeof(tid));
  std::memcpy(id + 4, &k, sizeof(k));
}

int main() {
  const char* path = "/dev/shm/ray_tpu_shm_stress";
  ::unlink(path);  // stale file from a previous (aborted) run
  void* store = shm_store_create(path, 64ull << 20);
  if (!store) {
    std::fprintf(stderr, "create failed\n");
    return 2;
  }
  uint8_t* base = shm_base(store);
  std::atomic<int> failures{0};

  const int kThreads = 8, kIters = 400;
  std::vector<std::thread> threads;
  threads.reserve(kThreads);
  for (int t = 0; t < kThreads; ++t) {
    threads.emplace_back([&, t]() {
      // every thread opens its own handle: cross-process mapping path
      void* h = shm_store_open(path);
      if (!h) { failures++; return; }
      uint8_t* b = shm_base(h);
      uint8_t id[16];
      for (int k = 0; k < kIters; ++k) {
        make_id(id, t, k);
        uint64_t size = 128 + (k % 7) * 512;
        uint64_t off = shm_create(h, id, size);
        if (off == UINT64_MAX) continue;  // store full: fine, LRU is Python-side
        std::memset(b + off, t, size);
        if (shm_seal(h, id) != 0) { failures++; continue; }
        uint64_t got_size = 0;
        uint64_t goff = shm_get(h, id, &got_size);
        if (goff == UINT64_MAX || got_size != size) { failures++; continue; }
        if ((b + goff)[size - 1] != (uint8_t)t) failures++;
        shm_release(h, id);
        if (k % 3 == 0) shm_delete(h, id);
        // read a neighbour thread's recent object (shared-slot contention)
        uint8_t other[16];
        make_id(other, (t + 1) % kThreads, k > 10 ? k - 10 : 0);
        uint64_t osz = 0;
        uint64_t ooff = shm_get(h, other, &osz);
        if (ooff != UINT64_MAX) {
          volatile uint8_t x = (b + ooff)[0];
          (void)x;
          shm_release(h, other);
        }
      }
      shm_store_close(h);
    });
  }
  for (auto& th : threads) th.join();

  uint64_t cap = 0, used = 0, objs = 0, evs = 0;
  shm_stats(store, &cap, &used, &objs, &evs);
  std::printf("stress done: failures=%d used=%llu objects=%llu\n",
              failures.load(), (unsigned long long)used,
              (unsigned long long)objs);
  shm_store_close(store);
  (void)base;
  return failures.load() == 0 ? 0 : 1;
}
