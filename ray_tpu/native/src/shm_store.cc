// Shared-memory host object store.
//
// TPU-native counterpart of the reference's Plasma store
// (src/ray/object_manager/plasma/: ObjectStore object_store.h:74,
// EvictionPolicy/LRUCache eviction_policy.h:105, dlmalloc slabs) —
// re-designed, not ported: one mmap'd file (tmpfs) holholding a
// boundary-tag free-list allocator, an open-addressing object table and
// an LRU list, ALL inside the mapping, guarded by one process-shared
// mutex, so any process that maps the file gets zero-copy reads of
// sealed objects with no broker daemon in the data path (the reference
// brokers create/seal over a unix socket; in-process C calls here).
//
// Exposed as a C ABI for ctypes (no pybind11 in this image).

#include <pthread.h>
#include <stdint.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <fcntl.h>
#include <unistd.h>

#include <atomic>
#include <new>
#include <errno.h>

namespace {

constexpr uint64_t kMagic = 0x5261795450755354ULL;  // "RayTPuST"
constexpr uint32_t kTableSlots = 1 << 16;           // object table capacity
constexpr uint64_t kAlign = 64;                     // cacheline alignment

struct ObjectEntry {
  uint8_t id[16];       // object id (all-zero = empty slot)
  uint64_t offset;      // data offset from region start
  uint64_t size;        // requested bytes (what the client sees)
  uint64_t alloc_size;  // bytes actually taken from the free list
  int32_t refcount;
  uint8_t sealed;
  uint8_t used;         // slot occupied (distinguishes tombstones)
  uint16_t _pad;
  uint64_t lru_tick;    // last zero-ref touch (for LRU eviction)
};

// free block header, kept inside the data region
struct FreeBlock {
  uint64_t size;        // includes header
  uint64_t next;        // offset of next free block (0 = none)
};

struct Header {
  uint64_t magic;
  uint64_t capacity;       // data region bytes
  uint64_t data_start;     // offset of data region from mapping base
  uint64_t free_head;      // offset of first free block (0 = none)
  uint64_t used_bytes;
  uint64_t num_objects;
  uint64_t lru_clock;
  uint64_t num_evictions;
  uint64_t max_probe;      // longest insert displacement (bounds miss scans)
  uint64_t failed;         // set when post-crash validation finds corruption
  pthread_mutex_t mutex;   // process-shared
  ObjectEntry table[kTableSlots];
};

struct Store {
  Header* hdr;
  uint8_t* base;
  uint64_t map_size;
  int fd;
};

uint64_t align_up(uint64_t v, uint64_t a) { return (v + a - 1) & ~(a - 1); }

uint32_t slot_hash(const uint8_t* id) {
  uint64_t h = 1469598103934665603ULL;
  for (int i = 0; i < 16; i++) { h ^= id[i]; h *= 1099511628211ULL; }
  return (uint32_t)(h & (kTableSlots - 1));
}

bool id_zero(const uint8_t* id) {
  for (int i = 0; i < 16; i++) if (id[i]) return false;
  return true;
}

ObjectEntry* find_entry(Header* h, const uint8_t* id) {
  uint32_t s = slot_hash(id);
  // probes bounded by the longest displacement any insert ever needed, so
  // delete tombstones cannot degrade misses into full-table scans
  for (uint32_t i = 0; i <= h->max_probe && i < kTableSlots; i++) {
    ObjectEntry* e = &h->table[(s + i) & (kTableSlots - 1)];
    if (!e->used && id_zero(e->id)) return nullptr;  // never-used slot: stop
    if (e->used && memcmp(e->id, id, 16) == 0) return e;
  }
  return nullptr;
}

ObjectEntry* find_free_slot(Header* h, const uint8_t* id) {
  uint32_t s = slot_hash(id);
  for (uint32_t i = 0; i < kTableSlots; i++) {
    ObjectEntry* e = &h->table[(s + i) & (kTableSlots - 1)];
    if (!e->used) {
      if (i > h->max_probe) h->max_probe = i;
      return e;
    }
  }
  return nullptr;  // table full
}

// -- allocator: first-fit free list with coalescing -------------------------

uint64_t alloc_bytes(Header* h, uint8_t* base, uint64_t want, uint64_t* got) {
  want = align_up(want, kAlign);
  uint64_t prev_off = 0;
  uint64_t cur = h->free_head;
  while (cur) {
    FreeBlock* fb = (FreeBlock*)(base + cur);
    if (fb->size >= want) {  // exact fit allowed
      uint64_t remain = fb->size - want;
      if (remain >= sizeof(FreeBlock) + kAlign) {
        // split: allocate from the front, shrink the free block
        uint64_t new_off = cur + want;
        FreeBlock* nb = (FreeBlock*)(base + new_off);
        nb->size = remain;
        nb->next = fb->next;
        if (prev_off) ((FreeBlock*)(base + prev_off))->next = new_off;
        else h->free_head = new_off;
        h->used_bytes += want;
        *got = want;
        return cur;
      }
      // take whole block
      if (prev_off) ((FreeBlock*)(base + prev_off))->next = fb->next;
      else h->free_head = fb->next;
      h->used_bytes += fb->size;
      *got = fb->size;  // whole block: caller must free this many bytes
      return cur;
    }
    prev_off = cur;
    cur = fb->next;
  }
  return 0;  // out of memory (offset 0 is the header, never valid for data)
}

void free_bytes(Header* h, uint8_t* base, uint64_t off, uint64_t size) {
  size = align_up(size, kAlign);
  // insert sorted by offset, coalesce with neighbours
  uint64_t prev = 0, cur = h->free_head;
  while (cur && cur < off) { prev = cur; cur = ((FreeBlock*)(base + cur))->next; }
  FreeBlock* nb = (FreeBlock*)(base + off);
  nb->size = size;
  nb->next = cur;
  if (prev) ((FreeBlock*)(base + prev))->next = off;
  else h->free_head = off;
  h->used_bytes -= size;
  // coalesce forward
  if (cur && off + nb->size == cur) {
    FreeBlock* cb = (FreeBlock*)(base + cur);
    nb->size += cb->size;
    nb->next = cb->next;
  }
  // coalesce backward
  if (prev) {
    FreeBlock* pb = (FreeBlock*)(base + prev);
    if (prev + pb->size == off) {
      pb->size += nb->size;
      pb->next = nb->next;
    }
  }
}

// evict LRU sealed zero-ref objects until at least `need` is allocatable
bool evict_for(Header* h, uint8_t* base, uint64_t need) {
  for (;;) {
    uint64_t got = 0;
    uint64_t probe = alloc_bytes(h, base, need, &got);
    if (probe) {
      // give it back; caller re-allocates (keeps one code path)
      free_bytes(h, base, probe, got);
      return true;
    }
    // find LRU victim
    ObjectEntry* victim = nullptr;
    for (uint32_t i = 0; i < kTableSlots; i++) {
      ObjectEntry* e = &h->table[i];
      if (e->used && e->sealed && e->refcount == 0) {
        if (!victim || e->lru_tick < victim->lru_tick) victim = e;
      }
    }
    if (!victim) return false;
    free_bytes(h, base, victim->offset, victim->alloc_size);
    victim->used = 0;
    memset(victim->id, 0xFF, 16);  // tombstone (non-zero keeps probes alive)
    h->num_objects--;
    h->num_evictions++;
  }
}

}  // namespace

extern "C" {

// returns NULL on failure. capacity = data region bytes.
void* shm_store_create(const char* path, uint64_t capacity) {
  uint64_t data_start = align_up(sizeof(Header), kAlign);
  uint64_t map_size = data_start + align_up(capacity, kAlign);
  int fd = open(path, O_RDWR | O_CREAT | O_EXCL, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, (off_t)map_size) != 0) { close(fd); unlink(path); return nullptr; }
  void* mem = mmap(nullptr, map_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) { close(fd); unlink(path); return nullptr; }
  Header* h = new (mem) Header();
  memset(h->table, 0, sizeof(h->table));
  h->magic = kMagic;
  h->capacity = align_up(capacity, kAlign);
  h->data_start = data_start;
  h->used_bytes = 0;
  h->num_objects = 0;
  h->lru_clock = 1;
  h->num_evictions = 0;
  h->max_probe = 0;
  h->failed = 0;
  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->mutex, &attr);
  // one big free block
  FreeBlock* fb = (FreeBlock*)((uint8_t*)mem + data_start);
  fb->size = h->capacity;
  fb->next = 0;
  h->free_head = data_start;

  Store* s = new Store{h, (uint8_t*)mem, map_size, fd};
  return s;
}

void* shm_store_open(const char* path) {
  int fd = open(path, O_RDWR);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) { close(fd); return nullptr; }
  void* mem = mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE,
                   MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) { close(fd); return nullptr; }
  Header* h = (Header*)mem;
  if (h->magic != kMagic) { munmap(mem, (size_t)st.st_size); close(fd); return nullptr; }
  Store* s = new Store{h, (uint8_t*)mem, (uint64_t)st.st_size, fd};
  return s;
}

void shm_store_close(void* store) {
  Store* s = (Store*)store;
  munmap(s->base, s->map_size);
  close(s->fd);
  delete s;
}

// A process died holding the lock, possibly mid-allocate/free. Before
// trusting the header, bound-check the free list and object table and
// recompute the byte accounting; if the structures don't validate, mark
// the store failed so every subsequent op errors instead of operating on
// crossed free-list links / double-allocated ranges.
static bool validate_after_owner_death(Header* h, uint8_t* base) {
  const uint64_t lo = h->data_start;
  const uint64_t hi = h->data_start + h->capacity;
  // gather every claimed interval (free blocks + live objects); any
  // overlap means a range is double-owned (e.g. death inside shm_delete
  // between free_bytes and clearing the entry) — unrecoverable.
  struct Interval { uint64_t off, end; };
  const uint64_t kMaxIvs = kTableSlots + 1024;  // free list is coalesced: short
  Interval* ivs = new (std::nothrow) Interval[kMaxIvs];
  if (!ivs) return false;
  struct IvGuard { Interval* p; ~IvGuard() { delete[] p; } } guard{ivs};
  uint64_t n_iv = 0;
  // free list: in-bounds, aligned, strictly ascending
  uint64_t free_total = 0, prev_end = 0, cur = h->free_head;
  uint64_t max_iters = h->capacity / kAlign + 2;
  while (cur) {
    if (cur < lo || cur >= hi || (cur & (kAlign - 1)) || !max_iters--) return false;
    FreeBlock* fb = (FreeBlock*)(base + cur);
    if (fb->size < kAlign || (fb->size & (kAlign - 1)) || cur + fb->size > hi)
      return false;
    if (cur < prev_end) return false;  // overlap / out of order
    prev_end = cur + fb->size;
    free_total += fb->size;
    if (n_iv < kMaxIvs)
      ivs[n_iv++] = {cur, cur + fb->size};
    else
      return false;  // absurd free-list length: treat as corrupt
    cur = fb->next;
  }
  // object table: entries in-bounds; recompute totals
  uint64_t used_total = 0, n_obj = 0;
  for (uint32_t i = 0; i < kTableSlots; i++) {
    ObjectEntry* e = &h->table[i];
    if (!e->used) continue;
    if (e->offset < lo || e->offset >= hi || e->alloc_size == 0 ||
        (e->alloc_size & (kAlign - 1)) || e->offset + e->alloc_size > hi ||
        e->refcount < 0)
      return false;
    used_total += e->alloc_size;
    n_obj++;
    if (n_iv < kMaxIvs)
      ivs[n_iv++] = {e->offset, e->offset + e->alloc_size};
    else
      return false;
  }
  if (free_total + used_total > h->capacity) return false;
  // sort intervals by offset (insertion sort: list is near-sorted — free
  // blocks arrive ascending) and reject any adjacent overlap
  for (uint64_t i = 1; i < n_iv; i++) {
    Interval key = ivs[i];
    uint64_t j = i;
    while (j > 0 && ivs[j - 1].off > key.off) { ivs[j] = ivs[j - 1]; j--; }
    ivs[j] = key;
  }
  for (uint64_t i = 1; i < n_iv; i++)
    if (ivs[i].off < ivs[i - 1].end) return false;  // double-owned range
  // repair the counters the dead owner may have half-updated
  h->used_bytes = used_total;
  h->num_objects = n_obj;
  return true;
}

static int lock_hdr(Header* h, uint8_t* base) {
  int rc = pthread_mutex_lock(&h->mutex);
  if (rc == EOWNERDEAD) {
    pthread_mutex_consistent(&h->mutex);
    if (!validate_after_owner_death(h, base)) h->failed = 1;
    rc = 0;
  }
  if (rc == 0 && h->failed) {
    pthread_mutex_unlock(&h->mutex);
    return EBADFD;
  }
  return rc;
}

// create an unsealed object; returns data offset from mapping base, 0 on
// failure (exists / no space even after eviction / table full).
uint64_t shm_create(void* store, const uint8_t* id, uint64_t size) {
  Store* s = (Store*)store;
  Header* h = s->hdr;
  if (size == 0) size = kAlign;
  if (lock_hdr(h, s->base)) return 0;
  uint64_t out = 0;
  do {
    if (find_entry(h, id)) break;  // already exists
    uint64_t got = 0;
    uint64_t off = alloc_bytes(h, s->base, size, &got);
    if (!off) {
      if (!evict_for(h, s->base, align_up(size, kAlign))) break;
      off = alloc_bytes(h, s->base, size, &got);
      if (!off) break;
    }
    ObjectEntry* e = find_free_slot(h, id);
    if (!e) { free_bytes(h, s->base, off, got); break; }
    memcpy(e->id, id, 16);
    e->offset = off;
    e->size = size;
    e->alloc_size = got;
    e->refcount = 1;  // creator holds a ref until seal+release
    e->sealed = 0;
    e->used = 1;
    e->lru_tick = 0;
    h->num_objects++;
    out = off;
  } while (0);
  pthread_mutex_unlock(&h->mutex);
  return out;
}

int shm_seal(void* store, const uint8_t* id) {
  Store* s = (Store*)store;
  Header* h = s->hdr;
  if (lock_hdr(h, s->base)) return -1;
  ObjectEntry* e = find_entry(h, id);
  int rc = -1;
  if (e && !e->sealed) { e->sealed = 1; rc = 0; }
  pthread_mutex_unlock(&h->mutex);
  return rc;
}

// get a sealed object: returns offset, fills size; takes a reference.
// 0 if missing or unsealed.
uint64_t shm_get(void* store, const uint8_t* id, uint64_t* size_out) {
  Store* s = (Store*)store;
  Header* h = s->hdr;
  if (lock_hdr(h, s->base)) return 0;
  uint64_t off = 0;
  ObjectEntry* e = find_entry(h, id);
  if (e && e->sealed) {
    e->refcount++;
    if (size_out) *size_out = e->size;
    off = e->offset;
  }
  pthread_mutex_unlock(&h->mutex);
  return off;
}

int shm_release(void* store, const uint8_t* id) {
  Store* s = (Store*)store;
  Header* h = s->hdr;
  if (lock_hdr(h, s->base)) return -1;
  int rc = -1;
  ObjectEntry* e = find_entry(h, id);
  if (e && e->refcount > 0) {
    e->refcount--;
    if (e->refcount == 0) e->lru_tick = h->lru_clock++;
    rc = 0;
  }
  pthread_mutex_unlock(&h->mutex);
  return rc;
}

int shm_delete(void* store, const uint8_t* id) {
  Store* s = (Store*)store;
  Header* h = s->hdr;
  if (lock_hdr(h, s->base)) return -1;
  int rc = -1;
  ObjectEntry* e = find_entry(h, id);
  if (e && e->refcount == 0) {
    free_bytes(h, s->base, e->offset, e->alloc_size);
    e->used = 0;
    memset(e->id, 0xFF, 16);
    h->num_objects--;
    rc = 0;
  }
  pthread_mutex_unlock(&h->mutex);
  return rc;
}

// reclaim regardless of refcount: for objects whose referencing process
// died (the reference reclaims plasma refs on client disconnect; with no
// broker the surviving peer must do it explicitly).
int shm_force_delete(void* store, const uint8_t* id) {
  Store* s = (Store*)store;
  Header* h = s->hdr;
  if (lock_hdr(h, s->base)) return -1;
  int rc = -1;
  ObjectEntry* e = find_entry(h, id);
  if (e) {
    free_bytes(h, s->base, e->offset, e->alloc_size);
    e->used = 0;
    memset(e->id, 0xFF, 16);
    h->num_objects--;
    rc = 0;
  }
  pthread_mutex_unlock(&h->mutex);
  return rc;
}

int shm_contains(void* store, const uint8_t* id) {
  Store* s = (Store*)store;
  Header* h = s->hdr;
  if (lock_hdr(h, s->base)) return 0;
  ObjectEntry* e = find_entry(h, id);
  int rc = (e && e->sealed) ? 1 : 0;
  pthread_mutex_unlock(&h->mutex);
  return rc;
}

uint8_t* shm_base(void* store) { return ((Store*)store)->base; }

void shm_stats(void* store, uint64_t* capacity, uint64_t* used,
               uint64_t* num_objects, uint64_t* num_evictions) {
  Store* s = (Store*)store;
  Header* h = s->hdr;
  if (capacity) *capacity = 0;
  if (used) *used = 0;
  if (num_objects) *num_objects = 0;
  if (num_evictions) *num_evictions = 0;
  if (lock_hdr(h, s->base)) return;  // failed store: zeroed outputs
  if (capacity) *capacity = h->capacity;
  if (used) *used = h->used_bytes;
  if (num_objects) *num_objects = h->num_objects;
  if (num_evictions) *num_evictions = h->num_evictions;
  pthread_mutex_unlock(&h->mutex);
}

}  // extern "C"
