"""ctypes binding for the native RPC frame reader (src/framing.cc).

Opt-in (RAY_TPU_NATIVE_FRAMING=1): the cluster RPC client's receive
loop then blocks inside C with the GIL released — no Python recv loop,
no bytes concatenation. The task-plane profile
(benchmarks/PROFILE_taskplane_r05.md) shows per-frame Python overhead
is a minor term on this host, which is why the flag defaults off; it
exists so multi-core deployments can measure it honestly.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

_LIB_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None


def load_library(build: bool = True) -> ctypes.CDLL:
    global _LIB
    with _LIB_LOCK:
        if _LIB is not None:
            return _LIB
        d = os.path.dirname(os.path.abspath(__file__))
        so = os.path.join(d, "libframing.so")
        if build:
            import fcntl

            src = os.path.join(d, "src", "framing.cc")
            stamp = os.path.join(d, ".framing.srchash")
            with open(src, "rb") as f:
                src_hash = hashlib.sha256(f.read()).hexdigest()
            with open(os.path.join(d, ".build.lock"), "w") as lockf:
                fcntl.flock(lockf, fcntl.LOCK_EX)
                try:
                    stamped = None
                    if os.path.exists(stamp):
                        with open(stamp) as f:
                            stamped = f.read().strip()
                    if not os.path.exists(so) or stamped != src_hash:
                        subprocess.run(
                            ["make", "-s", "-C", d, "libframing.so"],
                            check=True, capture_output=True,
                        )
                        with open(stamp, "w") as f:
                            f.write(src_hash)
                finally:
                    fcntl.flock(lockf, fcntl.LOCK_UN)
        lib = ctypes.CDLL(so)
        lib.frame_read.restype = ctypes.c_long
        lib.frame_read.argtypes = [
            ctypes.c_int, ctypes.POINTER(ctypes.POINTER(ctypes.c_ubyte)),
            ctypes.c_int,
        ]
        lib.frame_free.argtypes = [ctypes.POINTER(ctypes.c_ubyte)]
        lib.frame_write.restype = ctypes.c_int
        lib.frame_write.argtypes = [
            ctypes.c_int, ctypes.c_char_p, ctypes.c_ulong, ctypes.c_int
        ]
        _LIB = lib
        return lib


class FrameReader:
    """Blocking frame reader over a connected socket's fd.

    ``timeout_ms`` bounds every C-side poll: a peer that stalls
    MID-FRAME surfaces as connection loss (None) instead of wedging the
    reader forever; an IDLE expiry (no frame started) just loops —
    after calling ``should_stop`` so the owner can shut the loop down.
    """

    def __init__(self, fileno: int, timeout_ms: int = -1,
                 should_stop=None):
        self._lib = load_library()
        self._fd = fileno
        self._timeout_ms = int(timeout_ms)
        self._should_stop = should_stop

    def read_frame(self) -> Optional[bytes]:
        """One complete frame body, or None on EOF/connection loss/stop."""
        out = ctypes.POINTER(ctypes.c_ubyte)()
        while True:
            n = self._lib.frame_read(self._fd, ctypes.byref(out),
                                     self._timeout_ms)
            if n == -3:  # idle: nothing consumed, safe to keep waiting
                if self._should_stop is not None and self._should_stop():
                    return None
                continue
            if n == -1:
                return None
            if n < 0:
                raise MemoryError("native frame_read failed (oversized/alloc)")
            try:
                return ctypes.string_at(out, n)
            finally:
                self._lib.frame_free(out)


def enabled() -> bool:
    return os.environ.get("RAY_TPU_NATIVE_FRAMING", "") not in ("", "0")
