"""ctypes binding for the C++ shared-memory object store.

Reference analog: the plasma client (src/ray/object_manager/plasma/
client.cc) — but there is no broker socket: every process maps the same
tmpfs file and calls into libshm_store directly; sealed objects are
zero-copy numpy/memoryview slices of the mapping.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_LIB_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None


def _build_dir() -> str:
    return os.path.dirname(os.path.abspath(__file__))


def load_library(build: bool = True) -> ctypes.CDLL:
    """Load (building if needed) libshm_store.so.

    The .so is a build artifact (gitignored); staleness is decided by a
    source-hash stamp written after each build — mtimes are meaningless
    after a fresh git checkout.
    """
    global _LIB
    with _LIB_LOCK:
        if _LIB is not None:
            return _LIB
        d = _build_dir()
        so = os.path.join(d, "libshm_store.so")
        if build:
            import fcntl

            src = os.path.join(d, "src", "shm_store.cc")
            stamp = os.path.join(d, ".shm_store.srchash")
            with open(src, "rb") as f:
                src_hash = hashlib.sha256(f.read()).hexdigest()
            # cross-PROCESS build lock: N daemons starting together must
            # not race one `make` (a half-written .so fails to dlopen)
            with open(os.path.join(d, ".build.lock"), "w") as lockf:
                fcntl.flock(lockf, fcntl.LOCK_EX)
                try:
                    stamped = None
                    if os.path.exists(stamp):
                        with open(stamp) as f:
                            stamped = f.read().strip()
                    if not os.path.exists(so) or stamped != src_hash:
                        subprocess.run(
                            ["make", "-s", "-C", d], check=True,
                            capture_output=True,
                        )
                        with open(stamp, "w") as f:
                            f.write(src_hash)
                finally:
                    fcntl.flock(lockf, fcntl.LOCK_UN)
        lib = ctypes.CDLL(so)
        lib.shm_store_create.restype = ctypes.c_void_p
        lib.shm_store_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.shm_store_open.restype = ctypes.c_void_p
        lib.shm_store_open.argtypes = [ctypes.c_char_p]
        lib.shm_store_close.argtypes = [ctypes.c_void_p]
        lib.shm_create.restype = ctypes.c_uint64
        lib.shm_create.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
        lib.shm_seal.restype = ctypes.c_int
        lib.shm_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.shm_get.restype = ctypes.c_uint64
        lib.shm_get.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64)
        ]
        lib.shm_release.restype = ctypes.c_int
        lib.shm_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.shm_delete.restype = ctypes.c_int
        lib.shm_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.shm_force_delete.restype = ctypes.c_int
        lib.shm_force_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.shm_contains.restype = ctypes.c_int
        lib.shm_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.shm_base.restype = ctypes.c_void_p
        lib.shm_base.argtypes = [ctypes.c_void_p]
        lib.shm_stats.argtypes = [ctypes.c_void_p] + [
            ctypes.POINTER(ctypes.c_uint64)
        ] * 4
        _LIB = lib
        return lib


class ShmObjectStore:
    """One store = one tmpfs file. The creating process owns the file's
    lifetime; other processes attach with open()."""

    def __init__(self, lib: ctypes.CDLL, handle: int, path: str, owner: bool):
        self._lib = lib
        self._h = handle
        self.path = path
        self._owner = owner
        self._closed = False

    # -- lifecycle ------------------------------------------------------------

    @classmethod
    def create(cls, path: str, capacity: int) -> "ShmObjectStore":
        lib = load_library()
        h = lib.shm_store_create(path.encode(), capacity)
        if not h:
            raise OSError(f"failed to create shm store at {path}")
        return cls(lib, h, path, owner=True)

    @classmethod
    def open(cls, path: str) -> "ShmObjectStore":
        lib = load_library()
        h = lib.shm_store_open(path.encode())
        if not h:
            raise OSError(f"failed to open shm store at {path}")
        return cls(lib, h, path, owner=False)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._lib.shm_store_close(self._h)
        if self._owner:
            try:
                os.unlink(self.path)
            except OSError:
                pass

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    # -- object API -----------------------------------------------------------

    @staticmethod
    def _id16(object_id: bytes) -> bytes:
        if len(object_id) != 16:
            object_id = (object_id + b"\x00" * 16)[:16]
        return object_id

    def _check_open(self) -> None:
        # a dangling handle would be a SIGSEGV in C; fail in Python instead
        if self._closed:
            raise OSError(f"shm store {self.path} is closed")

    def create_buffer(self, object_id: bytes, size: int) -> memoryview:
        """Allocate an unsealed object; returns a writable view."""
        self._check_open()
        oid = self._id16(object_id)
        off = self._lib.shm_create(self._h, oid, size)
        if off == 0:
            raise MemoryError(
                f"shm store cannot allocate {size} bytes (exists or full)"
            )
        base = self._lib.shm_base(self._h)
        return (ctypes.c_uint8 * size).from_address(base + off), off

    def put(self, object_id: bytes, data: bytes) -> None:
        """create + write + seal + release in one call."""
        buf, _ = self.create_buffer(object_id, max(1, len(data)))
        ctypes.memmove(buf, data, len(data))
        self.seal(object_id)
        self.release(object_id)

    def put_pinned(self, object_id: bytes, data: bytes) -> bool:
        """create + write + seal, KEEPING the creator reference — the
        object is pinned against LRU eviction until release()/delete().
        Returns False (instead of raising) when the store is full or the
        id already exists; the one sealing protocol both the daemon and
        workers use."""
        if len(data) == 0:
            return False  # store rounds 0 up to 1 byte: size would lie
        try:
            buf, _ = self.create_buffer(object_id, len(data))
            ctypes.memmove(buf, data, len(data))
            self.seal(object_id)
        except (MemoryError, OSError, KeyError):
            return False
        return True

    def get_slice(self, object_id: bytes, offset: int,
                  length: int) -> Optional[bytes]:
        """Copy out one slice of a sealed object (chunked cross-node
        serving must not memcpy the WHOLE object per chunk)."""
        view = self.get(object_id)
        if view is None:
            return None
        try:
            return bytes(view[offset:offset + length])
        finally:
            self.release(object_id)

    def size_of(self, object_id: bytes) -> Optional[int]:
        view = self.get(object_id)
        if view is None:
            return None
        try:
            return len(view)
        finally:
            self.release(object_id)

    def seal(self, object_id: bytes) -> None:
        self._check_open()
        if self._lib.shm_seal(self._h, self._id16(object_id)) != 0:
            raise KeyError(f"cannot seal {object_id!r} (missing or sealed)")

    def get(self, object_id: bytes) -> Optional[memoryview]:
        """Zero-copy read view of a sealed object (takes a reference —
        call release() when done)."""
        self._check_open()
        size = ctypes.c_uint64()
        off = self._lib.shm_get(self._h, self._id16(object_id), ctypes.byref(size))
        if off == 0:
            return None
        base = self._lib.shm_base(self._h)
        arr = np.ctypeslib.as_array(
            (ctypes.c_uint8 * size.value).from_address(base + off)
        )
        return memoryview(arr)

    def get_bytes(self, object_id: bytes) -> Optional[bytes]:
        view = self.get(object_id)
        if view is None:
            return None
        try:
            return bytes(view)
        finally:
            self.release(object_id)

    def release(self, object_id: bytes) -> None:
        self._check_open()
        self._lib.shm_release(self._h, self._id16(object_id))

    def delete(self, object_id: bytes) -> bool:
        self._check_open()
        return self._lib.shm_delete(self._h, self._id16(object_id)) == 0

    def force_delete(self, object_id: bytes) -> bool:
        """Reclaim regardless of refcount — for objects whose referencing
        process died holding refs (plasma reclaims on client disconnect;
        with no broker the surviving peer does it explicitly)."""
        self._check_open()
        return self._lib.shm_force_delete(self._h, self._id16(object_id)) == 0

    def contains(self, object_id: bytes) -> bool:
        self._check_open()
        return bool(self._lib.shm_contains(self._h, self._id16(object_id)))

    def stats(self) -> dict:
        self._check_open()
        vals = [ctypes.c_uint64() for _ in range(4)]
        self._lib.shm_stats(self._h, *[ctypes.byref(v) for v in vals])
        return {
            "capacity": vals[0].value,
            "used": vals[1].value,
            "num_objects": vals[2].value,
            "num_evictions": vals[3].value,
        }
