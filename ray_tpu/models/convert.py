"""HuggingFace -> ray_tpu weight conversion for the llama family.

Reference analog: the reference loads any HF checkpoint by delegating
to vLLM's loader; here the mapping is explicit — a transformers
LlamaForCausalLM state dict (same layout Mistral/Qwen2/TinyLlama use)
becomes this framework's stacked-layer param tree:

  * torch Linear weights are [out, in] -> transposed to [in, out];
  * per-layer tensors stack along a leading layer axis (lax.scan
    layout, models/llama.py);
  * RoPE needs no permutation: both sides use the half-split
    (rotate_half) convention with inv-freq over arange(0, d, 2).

Parity is proven in tests/test_hf_convert.py: a randomly-initialized
transformers model's logits match this framework's forward on the
converted weights.
"""

from __future__ import annotations

from typing import Any, Mapping

import jax.numpy as jnp
import numpy as np

from ray_tpu.models import llama


def _a(x) -> np.ndarray:
    """torch tensor / array -> fp32 numpy (numpy has no bf16: modern
    checkpoints are bf16, so the cast must happen torch-side)."""
    if hasattr(x, "detach"):
        return x.detach().float().cpu().numpy()
    return np.asarray(x, dtype=np.float32)


def _t(x) -> np.ndarray:
    """As _a, transposed ([out, in] torch Linear -> [in, out])."""
    return _a(x).T


def params_from_hf_state_dict(
    state_dict: Mapping[str, Any],
    config: llama.LlamaConfig,
    dtype=None,
) -> llama.Params:
    """Map a transformers llama-family state dict onto the param tree."""
    sd = {k.removeprefix("model."): v for k, v in state_dict.items()}
    dt = dtype or config.param_dtype
    L = config.n_layers
    consumed: set = set()

    def layer(name: str, transpose: bool) -> jnp.ndarray:
        conv = _t if transpose else _a
        keys = [f"layers.{i}.{name}" for i in range(L)]
        consumed.update(keys)
        return jnp.asarray(np.stack([conv(sd[k]) for k in keys]), dt)

    params: llama.Params = {
        "embed": jnp.asarray(_a(sd["embed_tokens.weight"]), dt),
        "layers": {
            "ln1": layer("input_layernorm.weight", transpose=False),
            "wq": layer("self_attn.q_proj.weight", transpose=True),
            "wk": layer("self_attn.k_proj.weight", transpose=True),
            "wv": layer("self_attn.v_proj.weight", transpose=True),
            "wo": layer("self_attn.o_proj.weight", transpose=True),
            "ln2": layer("post_attention_layernorm.weight", transpose=False),
            "w_gate": layer("mlp.gate_proj.weight", transpose=True),
            "w_up": layer("mlp.up_proj.weight", transpose=True),
            "w_down": layer("mlp.down_proj.weight", transpose=True),
        },
        "final_norm": jnp.asarray(_a(sd["norm.weight"]), dt),
    }
    consumed.update({"embed_tokens.weight", "norm.weight"})
    if config.tie_embeddings:
        # transformers emits the tied lm_head.weight anyway; a converted
        # lm_head key would mismatch init_params/logical_axes pytrees.
        # But dropping an UNTIED head silently mis-maps — verify the tie.
        if "lm_head.weight" in sd:
            head = _a(sd["lm_head.weight"])
            emb = _a(sd["embed_tokens.weight"])
            if head.shape != emb.shape or not np.allclose(head, emb):
                raise ValueError(
                    "config.tie_embeddings=True but the checkpoint's "
                    "lm_head.weight differs from embed_tokens.weight — "
                    "this is an untied checkpoint; set tie_embeddings=False"
                )
        consumed.add("lm_head.weight")
    elif "lm_head.weight" in sd:
        params["lm_head"] = jnp.asarray(_t(sd["lm_head.weight"]), dt)
        consumed.add("lm_head.weight")
    else:
        raise KeyError(
            "state dict has no lm_head.weight and config.tie_embeddings "
            "is False — set tie_embeddings=True for tied checkpoints"
        )
    # leftovers mean silently-wrong output (e.g. Qwen2's q/k/v biases,
    # which this decoder has no parameters for) — refuse, don't mis-map
    leftovers = {
        k for k in sd
        if k not in consumed and not k.endswith(("rotary_emb.inv_freq",))
    }
    if leftovers:
        raise ValueError(
            f"unmapped checkpoint tensors {sorted(leftovers)[:6]}... — this "
            "architecture carries parameters the llama-family decoder "
            "doesn't have (e.g. attention biases); conversion would be "
            "silently wrong"
        )
    return params


def load_hf_checkpoint(model_dir: str, config=None):
    """Convenience: (config, params) from a local HF checkpoint directory
    (config.json + safetensors/bin). No network access."""
    import json
    import os

    from ray_tpu.models.registry import config_from_hf

    with open(os.path.join(model_dir, "config.json")) as f:
        hf_cfg = json.load(f)
    if config is None:
        config = config_from_hf(hf_cfg)

    state: dict = {}
    st_files = [f for f in os.listdir(model_dir) if f.endswith(".safetensors")]
    if st_files:
        from safetensors import safe_open

        for fname in sorted(st_files):
            with safe_open(os.path.join(model_dir, fname), framework="np") as f:
                for k in f.keys():
                    state[k] = f.get_tensor(k)
    else:
        import torch

        for fname in sorted(os.listdir(model_dir)):
            # only weight shards: Trainer dirs also hold e.g.
            # training_args.bin, which is not a state dict
            if fname.startswith("pytorch_model") and fname.endswith(".bin"):
                state.update(
                    torch.load(os.path.join(model_dir, fname),
                               map_location="cpu", weights_only=True)
                )
    if not state:
        raise FileNotFoundError(f"no weight files in {model_dir}")
    return config, params_from_hf_state_dict(state, config)
