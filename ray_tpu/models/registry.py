"""Model registry: named presets + HuggingFace config mapping.

Reference analog: the reference serves any HF model id by delegating to
vLLM's model loader (llm/_internal/serve/deployments/llm/vllm/
vllm_models.py model_id plumbing). This framework's compute path is the
llama-family decoder (models/llama.py — which covers Llama 1/2/3,
Mistral, Qwen2, TinyLlama, ... since they share the architecture) and
the MoE variant (models/moe.py — Mixtral-style). The registry gives
users the same two entry points they expect:

  * `get_model_config("llama3-8b")` — named presets;
  * `config_from_hf(json.load(open("config.json")))` — map a HF
    transformers config dict onto LlamaConfig/MoEConfig (no downloads;
    weight conversion is a separate concern).
"""

from __future__ import annotations

import dataclasses
from typing import Any

from ray_tpu.models import llama, moe

_REGISTRY: dict[str, Any] = {}


def register_model(name: str, config) -> None:
    key = name.lower()
    if key in _REGISTRY:
        raise ValueError(f"model {name!r} already registered")
    _REGISTRY[key] = config


def get_model_config(name: str):
    """Named preset lookup (case-insensitive); returns a frozen config."""
    try:
        return _REGISTRY[name.lower()]
    except KeyError:
        raise KeyError(
            f"unknown model {name!r}; registered: {sorted(_REGISTRY)}"
        ) from None


def list_models() -> list[str]:
    return sorted(_REGISTRY)


# -- presets (architecture hyperparameters from the public model cards) ------

for _name, _cfg in {
    "llama3-8b": llama.LLAMA3_8B,
    "llama3-1b": llama.LLAMA3_1B,
    "llama-400m": llama.LLAMA_400M,
    "llama-tiny": llama.LLAMA_TINY,
    "llama3-70b": dataclasses.replace(
        llama.LLAMA3_8B, d_model=8192, n_layers=80, n_heads=64, n_kv_heads=8,
        d_ff=28672,
    ),
    "mistral-7b": dataclasses.replace(
        llama.LLAMA3_8B, vocab_size=32000, d_model=4096, n_layers=32,
        n_heads=32, n_kv_heads=8, d_ff=14336, rope_theta=10000.0,
        max_seq=32768,
    ),
    "qwen2-7b": dataclasses.replace(
        llama.LLAMA3_8B, vocab_size=152064, d_model=3584, n_layers=28,
        n_heads=28, n_kv_heads=4, d_ff=18944, rope_theta=1000000.0,
        max_seq=32768,
    ),
    "tinyllama-1.1b": dataclasses.replace(
        llama.LLAMA3_8B, vocab_size=32000, d_model=2048, n_layers=22,
        n_heads=32, n_kv_heads=4, d_ff=5632, rope_theta=10000.0,
        max_seq=2048,
    ),
    "mixtral-8x7b": moe.MIXTRAL_8X7B,
    "moe-tiny": moe.MOE_TINY,
}.items():
    register_model(_name, _cfg)


# -- HF transformers config.json mapping -------------------------------------

_HF_LLAMA_ARCHS = {
    "LlamaForCausalLM", "MistralForCausalLM", "Qwen2ForCausalLM",
}
_HF_MOE_ARCHS = {"MixtralForCausalLM"}


def config_from_hf(hf: dict, **overrides):
    """Map a HF `config.json` dict to a LlamaConfig/MoEConfig.

    Only architecture hyperparameters travel; framework knobs
    (dtype/remat/attention_impl) keep their TPU defaults unless
    overridden. Raises on architectures outside the llama/mixtral
    families rather than mis-mapping them.
    """
    archs = set(hf.get("architectures", ()))
    # the num_local_experts heuristic only applies to config dicts with NO
    # architectures field: PhiMoE/GPT-OSS-style configs also carry it and
    # must be rejected by the whitelist, not mapped onto Mixtral
    is_moe = bool(archs & _HF_MOE_ARCHS) or (
        not archs and "num_local_experts" in hf
    )
    if archs and not is_moe and not (archs & _HF_LLAMA_ARCHS):
        raise ValueError(
            f"unsupported architectures {sorted(archs)}; llama-family "
            f"({sorted(_HF_LLAMA_ARCHS)}) and mixtral-family "
            f"({sorted(_HF_MOE_ARCHS)}) map onto this framework's decoders"
        )
    scaling = hf.get("rope_scaling")
    if scaling and scaling.get("rope_type", scaling.get("type")) != "default":
        # llama-3.1-style frequency rescaling changes every position's
        # rotation; mapping rope_theta alone would diverge silently
        raise ValueError(
            f"rope_scaling={scaling!r} is not supported; only default RoPE "
            "maps onto this decoder"
        )
    derived_hd = hf["hidden_size"] // hf["num_attention_heads"]
    if hf.get("head_dim") not in (None, derived_hd):
        raise ValueError(
            f"explicit head_dim={hf['head_dim']} != hidden_size/"
            f"num_attention_heads={derived_hd}; this decoder derives "
            "head_dim and would mis-shape the checkpoint"
        )
    common = dict(
        vocab_size=hf["vocab_size"],
        d_model=hf["hidden_size"],
        n_layers=hf["num_hidden_layers"],
        n_heads=hf["num_attention_heads"],
        n_kv_heads=hf.get("num_key_value_heads", hf["num_attention_heads"]),
        d_ff=hf["intermediate_size"],
        max_seq=hf.get("max_position_embeddings", 8192),
        rope_theta=float(hf.get("rope_theta", 10000.0)),
        rms_eps=float(hf.get("rms_norm_eps", 1e-5)),
        tie_embeddings=bool(hf.get("tie_word_embeddings", False)),
    )
    if is_moe:
        common["n_experts"] = hf["num_local_experts"]
        common["top_k"] = hf.get("num_experts_per_tok", 2)
        common.update(overrides)  # caller wins on collisions
        return moe.MoEConfig(**common)
    common.update(overrides)
    return llama.LlamaConfig(**common)
