"""Llama inference paths over the paged KV cache.

The training forward (models/llama.py) recomputes all positions; these
entry points are the serving-engine counterparts (reference delegates
both to vLLM — vllm_engine.py):

 * `prefill` — run a batch of prompt suffixes, scatter their K/V into
   cache pages, attend over (cached prefix + suffix) via page gather,
   return last-position logits.
 * `decode_step` — one token per running sequence, scatter K/V to each
   sequence's next slot, paged attention over its pages.

Cache layout: k/v [n_layers, n_kv_heads, num_slots + 1, head_dim];
the extra final slot is the trash row padding writes land in.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from ray_tpu.models.llama import LlamaConfig, Params
from ray_tpu.nn.layers import apply_rope, rms_norm, rope_frequencies, swiglu
from ray_tpu.ops.paged_attention import paged_attention
from ray_tpu.ops.ragged import ragged_attention

Cache = dict[str, jax.Array]


def init_cache(config: LlamaConfig, num_slots: int, dtype=None,
               trash_slots: int = 16) -> Cache:
    """num_slots = num_blocks * block_size; a TRASH PAGE appended (pad
    rows scatter to slot `num_slots`) — a whole page, not one row, so the
    slot count stays a multiple of every block_size <= trash_slots and
    the Pallas kernel can view the cache pre-blocked.

    HEAD-MAJOR layout [L, KVH, slots, D]: the Pallas decode kernel
    fetches one page per kv head, and Mosaic requires the sliced
    (second-minor) dim be sublane-aligned — slots must therefore sit
    next to D, with the scalar-indexed head dim leading."""
    c = config
    shape = (c.n_layers, c.n_kv_heads, num_slots + trash_slots, c.head_dim)
    dt = dtype or c.dtype
    return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}


def _qkv(x, lp, c: LlamaConfig):
    B, S, _ = x.shape
    hd = c.head_dim
    q = jnp.einsum("bsd,dh->bsh", x, lp["wq"].astype(x.dtype)).reshape(B, S, c.n_heads, hd)
    k = jnp.einsum("bsd,dh->bsh", x, lp["wk"].astype(x.dtype)).reshape(B, S, c.n_kv_heads, hd)
    v = jnp.einsum("bsd,dh->bsh", x, lp["wv"].astype(x.dtype)).reshape(B, S, c.n_kv_heads, hd)
    return q, k, v


def _out_proj(o, lp, B, S, c: LlamaConfig):
    return jnp.einsum(
        "bsh,hd->bsd", o.reshape(B, S, c.n_heads * c.head_dim), lp["wo"].astype(o.dtype)
    )


def _unstack_layer(params_layers: Params, i) -> Params:
    return jax.tree.map(lambda x: x[i], params_layers)


def _lora_delta(x, A_l, B_l, ids):
    """Per-row LoRA delta: x [B,S,d], A_l [n_slots,d,r], B_l [n_slots,r,o],
    ids [B] (slot 0 = zero adapter). Returns [B,S,o]."""
    t = jnp.einsum("bsd,bdr->bsr", x, A_l[ids].astype(x.dtype))
    return jnp.einsum("bsr,bro->bso", t, B_l[ids].astype(x.dtype))


def _apply_lora(q, k, v, x, lora_l, ids, c: LlamaConfig):
    """Add per-sequence adapter deltas to the attention projections.

    lora_l: this layer's stacks {"wq_A": [n,d,r], "wq_B": [n,r,H*hd], ...}
    — mixed-adapter continuous batching: every row of the batch may use a
    different adapter (or none), selected by `ids` (reference role: LoRA
    multiplexing, llm/_internal/serve/deployments/llm/multiplex/)."""
    B, S, _ = x.shape
    hd = c.head_dim
    if "wq_A" in lora_l:
        q = q + _lora_delta(x, lora_l["wq_A"], lora_l["wq_B"], ids).reshape(
            B, S, c.n_heads, hd
        )
    if "wk_A" in lora_l:
        k = k + _lora_delta(x, lora_l["wk_A"], lora_l["wk_B"], ids).reshape(
            B, S, c.n_kv_heads, hd
        )
    if "wv_A" in lora_l:
        v = v + _lora_delta(x, lora_l["wv_A"], lora_l["wv_B"], ids).reshape(
            B, S, c.n_kv_heads, hd
        )
    return q, k, v


def _paged_forward(
    params: Params,
    tokens: jax.Array,       # [B, S_pad] suffix tokens (right-padded)
    positions: jax.Array,    # [B, S_pad] absolute positions (pad = 0)
    slot_mapping: jax.Array, # [B, S_pad] cache slots (pad -> trash slot)
    block_tables: jax.Array, # [B, MB]
    context_lens: jax.Array, # [B] prefix + suffix length
    cache: Cache,
    config: LlamaConfig,
    *,
    block_size: int,
    lora: "dict | None" = None,
) -> tuple[jax.Array, Cache]:
    """Shared multi-token transformer body over the paged cache: scatter
    the suffix K/V into pages, attend over (cached prefix + suffix) per
    layer, return the final hidden states [B, S, D] + updated cache.
    Both `prefill` (last-position logits) and `verify_tokens` (all-
    position logits, speculative-decoding verification) sit on top."""
    c = config
    B, S = tokens.shape
    if S > c.max_seq:
        raise ValueError(
            f"prefill chunk length {S} > max_seq={c.max_seq}; RoPE tables "
            "only cover max_seq positions (llama.forward has the same guard)"
        )
    cos, sin = rope_frequencies(c.head_dim, c.max_seq, c.rope_theta)
    h = params["embed"].astype(c.dtype)[tokens]
    flat_slots = slot_mapping.reshape(-1)  # [B*S]

    lora_ids = lora["ids"] if lora is not None else None
    lora_stacks = (
        {k_: v_ for k_, v_ in lora.items() if k_ != "ids"} if lora is not None else None
    )

    def layer_step(carry, xs):
        h, = carry
        if lora_stacks is not None:
            lp, k_cache_l, v_cache_l, lora_l = xs
        else:
            lp, k_cache_l, v_cache_l = xs
        x = rms_norm(h, lp["ln1"], c.rms_eps)
        q, k, v = _qkv(x, lp, c)
        if lora_stacks is not None:
            q, k, v = _apply_lora(q, k, v, x, lora_l, lora_ids, c)
        q = apply_rope(q, cos, sin, positions)
        k = apply_rope(k, cos, sin, positions)
        # scatter suffix K/V into this layer's pages (pad rows -> trash
        # slot); cache is head-major [KVH, slots, D]
        k_cache_l = k_cache_l.at[:, flat_slots].set(
            k.reshape(B * S, c.n_kv_heads, c.head_dim)
            .swapaxes(0, 1).astype(k_cache_l.dtype)
        )
        v_cache_l = v_cache_l.at[:, flat_slots].set(
            v.reshape(B * S, c.n_kv_heads, c.head_dim)
            .swapaxes(0, 1).astype(v_cache_l.dtype)
        )
        o = _page_attend_prefill(
            q, k_cache_l, v_cache_l, block_tables, context_lens, positions, c,
            block_size=block_size,
        )
        h = h + _out_proj(o, lp, B, S, c)
        x = rms_norm(h, lp["ln2"], c.rms_eps)
        h = h + swiglu(x, lp["w_gate"], lp["w_up"], lp["w_down"])
        return (h,), (k_cache_l, v_cache_l)

    xs = (params["layers"], cache["k"], cache["v"])
    if lora_stacks is not None:
        xs = xs + (lora_stacks,)
    (h,), (new_k, new_v) = jax.lax.scan(layer_step, (h,), xs)
    h = rms_norm(h, params["final_norm"], c.rms_eps)
    return h, {"k": new_k, "v": new_v}


def _lm_head(params: Params, h: jax.Array, c: LlamaConfig) -> jax.Array:
    w_out = params.get("lm_head", None)
    if w_out is None:
        w_out = params["embed"].T
    return jnp.einsum("...d,dv->...v", h, w_out.astype(c.dtype)).astype(jnp.float32)


def prefill(
    params: Params,
    tokens: jax.Array,       # [B, S_pad] suffix tokens (right-padded)
    positions: jax.Array,    # [B, S_pad] absolute positions (pad = 0)
    suffix_lens: jax.Array,  # [B] valid suffix tokens per row
    slot_mapping: jax.Array, # [B, S_pad] cache slots (pad -> trash slot)
    block_tables: jax.Array, # [B, MB]
    context_lens: jax.Array, # [B] prefix + suffix length
    cache: Cache,
    config: LlamaConfig,
    *,
    block_size: int,
    lora: "dict | None" = None,  # {"ids": [B], "<t>_A": [L,n,d,r], "<t>_B": [L,n,r,o]}
) -> tuple[jax.Array, Cache]:
    """Returns (last-valid-token logits [B, V], updated cache)."""
    h, new_cache = _paged_forward(
        params, tokens, positions, slot_mapping, block_tables, context_lens,
        cache, config, block_size=block_size, lora=lora,
    )
    S = tokens.shape[1]
    # only the last valid suffix position's logits matter per row
    last = jnp.clip(suffix_lens - 1, 0, S - 1)  # [B]
    h_last = jnp.take_along_axis(h, last[:, None, None], axis=1)[:, 0]  # [B, D]
    return _lm_head(params, h_last, config), new_cache


def verify_tokens(
    params: Params,
    tokens: jax.Array,       # [B, K+1] current token + K drafted (right-padded)
    positions: jax.Array,    # [B, K+1] absolute positions (pad = 0)
    slot_mapping: jax.Array, # [B, K+1] cache slots (pad -> trash slot)
    block_tables: jax.Array, # [B, MB]
    context_lens: jax.Array, # [B] prefix + valid suffix length
    cache: Cache,
    config: LlamaConfig,
    *,
    block_size: int,
    lora: "dict | None" = None,
) -> tuple[jax.Array, Cache]:
    """Speculative-decoding verification: score a short drafted suffix in
    ONE pass through the paged-KV prefill path and return logits at EVERY
    suffix position [B, K+1, V] (position j conditions on the fed tokens
    0..j — exactly the distributions the acceptance sampler needs).

    This converts K bandwidth-bound decode steps into one compute-dense
    multi-token pass: the weights stream from HBM once per K+1 tokens
    instead of once per token. Rows with an empty draft degenerate to a
    plain decode step (suffix = just the current token, pad columns write
    the trash slot and their logits are ignored)."""
    h, new_cache = _paged_forward(
        params, tokens, positions, slot_mapping, block_tables, context_lens,
        cache, config, block_size=block_size, lora=lora,
    )
    return _lm_head(params, h, config), new_cache


def _page_attend_prefill(
    q: jax.Array,            # [B, S, H, D] (rope'd)
    k_cache_l: jax.Array,    # [KVH, num_slots+1, D]
    v_cache_l: jax.Array,
    block_tables: jax.Array, # [B, MB]
    context_lens: jax.Array, # [B]
    positions: jax.Array,    # [B, S] absolute query positions
    c: LlamaConfig,
    *,
    block_size: int,
) -> jax.Array:
    """Gather the full paged context and run masked attention.
    mask: kv_pos <= q_pos (causal, absolute) AND kv_pos < context_len."""
    B, S, H, D = q.shape
    KVH = c.n_kv_heads
    G = H // KVH
    MB = block_tables.shape[1]
    S_kv = MB * block_size

    offs = jnp.arange(S_kv, dtype=jnp.int32)
    slots = block_tables[:, offs // block_size] * block_size + offs % block_size
    k = k_cache_l[:, slots]  # [KVH, B, S_kv, D] (head-major cache)
    v = v_cache_l[:, slots]

    qg = q.reshape(B, S, KVH, G, D).astype(jnp.float32)
    scores = jnp.einsum("bshgd,hbtd->bhgst", qg, k.astype(jnp.float32))
    scores *= 1.0 / jnp.sqrt(D).astype(jnp.float32)
    kv_pos = offs[None, :]  # [1, S_kv]
    valid = kv_pos < context_lens[:, None]  # [B, S_kv]
    causal = kv_pos[:, None, :] <= positions[:, :, None]  # [B, S, S_kv]
    mask = (valid[:, None, :] & causal)[:, None, None, :, :]  # [B,1,1,S,S_kv]
    scores = jnp.where(mask, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    probs = jnp.where(jnp.isnan(probs), 0.0, probs)  # fully-masked pad rows
    out = jnp.einsum("bhgst,hbtd->bshgd", probs, v.astype(jnp.float32))
    return out.reshape(B, S, H, D).astype(q.dtype)


def _apply_lora_packed(q, k, v, x, lora_l, ids, c: LlamaConfig):
    """Per-TOKEN LoRA deltas for packed ragged rows: x [1, T, D],
    ids [T] (slot 0 = zero adapter). The packed token axis is viewed as
    the batch axis [T, 1, D] so every packed row selects its own
    adapter — a mixed batch interleaves rows of different requests."""
    T = x.shape[1]
    xt = x[0][:, None]  # [T, 1, D]
    hd = c.head_dim
    if "wq_A" in lora_l:
        q = q + _lora_delta(xt, lora_l["wq_A"], lora_l["wq_B"], ids).reshape(
            1, T, c.n_heads, hd
        )
    if "wk_A" in lora_l:
        k = k + _lora_delta(xt, lora_l["wk_A"], lora_l["wk_B"], ids).reshape(
            1, T, c.n_kv_heads, hd
        )
    if "wv_A" in lora_l:
        v = v + _lora_delta(xt, lora_l["wv_A"], lora_l["wv_B"], ids).reshape(
            1, T, c.n_kv_heads, hd
        )
    return q, k, v


def ragged_forward(
    params: Params,
    tokens: jax.Array,       # [T] packed tokens (pad rows trail)
    positions: jax.Array,    # [T] absolute positions (pad = 0)
    slot_mapping: jax.Array, # [T] cache slots (pad -> trash slot)
    block_tables: jax.Array, # [B, MB]
    cu_q_lens: jax.Array,    # [B+1] exclusive prefix sums of row lengths
    context_lens: jax.Array, # [B] prefix + suffix length (pad seq = 0)
    cache: Cache,
    config: LlamaConfig,
    *,
    block_size: int,
    max_q_len: int,
    attn_impl: str = "auto",
    lora: "dict | None" = None,  # {"ids": [T] per-TOKEN, "<t>_A": ..., "<t>_B": ...}
) -> tuple[jax.Array, Cache]:
    """Packed ragged transformer body over the paged cache: the ONE
    program a mixed batch runs — prefill chunks and decode rows
    concatenated along a single token axis, each sequence delimited by
    `cu_q_lens`, attention via `ops/ragged.py`. Scatters the packed
    K/V into pages, returns final hidden states [T, D] + updated
    cache. `mixed_step` (last-row logits) and `verify_tokens_ragged`
    (per-row all-position logits) sit on top."""
    c = config
    T = tokens.shape[0]
    if max_q_len > c.max_seq:
        raise ValueError(
            f"max_q_len {max_q_len} > max_seq={c.max_seq}; RoPE tables "
            "only cover max_seq positions"
        )
    cos, sin = rope_frequencies(c.head_dim, c.max_seq, c.rope_theta)
    h = params["embed"].astype(c.dtype)[tokens][None]  # [1, T, D]
    pos2 = positions[None]  # [1, T]

    lora_ids = lora["ids"] if lora is not None else None
    lora_stacks = (
        {k_: v_ for k_, v_ in lora.items() if k_ != "ids"} if lora is not None else None
    )

    def layer_step(carry, xs):
        h, = carry
        if lora_stacks is not None:
            lp, k_cache_l, v_cache_l, lora_l = xs
        else:
            lp, k_cache_l, v_cache_l = xs
        x = rms_norm(h, lp["ln1"], c.rms_eps)
        q, k, v = _qkv(x, lp, c)
        if lora_stacks is not None:
            q, k, v = _apply_lora_packed(q, k, v, x, lora_l, lora_ids, c)
        q = apply_rope(q, cos, sin, pos2)
        k = apply_rope(k, cos, sin, pos2)
        # scatter packed K/V into this layer's pages (pad rows -> trash
        # slot); cache is head-major [KVH, slots, D]
        k_cache_l = k_cache_l.at[:, slot_mapping].set(
            k[0].swapaxes(0, 1).astype(k_cache_l.dtype)
        )
        v_cache_l = v_cache_l.at[:, slot_mapping].set(
            v[0].swapaxes(0, 1).astype(v_cache_l.dtype)
        )
        o = ragged_attention(
            q[0],
            k_cache_l,
            v_cache_l,
            block_tables,
            cu_q_lens,
            context_lens,
            block_size=block_size,
            max_q_len=max_q_len,
            impl=attn_impl,
        )[None]  # [1, T, H, D]
        h = h + _out_proj(o, lp, 1, T, c)
        x = rms_norm(h, lp["ln2"], c.rms_eps)
        h = h + swiglu(x, lp["w_gate"], lp["w_up"], lp["w_down"])
        return (h,), (k_cache_l, v_cache_l)

    xs = (params["layers"], cache["k"], cache["v"])
    if lora_stacks is not None:
        xs = xs + (lora_stacks,)
    (h,), (new_k, new_v) = jax.lax.scan(layer_step, (h,), xs)
    h = rms_norm(h[0], params["final_norm"], c.rms_eps)  # [T, D]
    return h, {"k": new_k, "v": new_v}


def mixed_step(
    params: Params,
    tokens: jax.Array,       # [T] packed tokens
    positions: jax.Array,    # [T]
    slot_mapping: jax.Array, # [T]
    block_tables: jax.Array, # [B, MB]
    cu_q_lens: jax.Array,    # [B+1]
    context_lens: jax.Array, # [B]
    cache: Cache,
    config: LlamaConfig,
    *,
    block_size: int,
    max_q_len: int,
    attn_impl: str = "auto",
    lora: "dict | None" = None,
) -> tuple[jax.Array, Cache]:
    """One mixed prefill+decode step -> (last-row logits [B, V], cache).

    Row b's logits condition on its full context including the packed
    suffix — for a decode row that is the next-token distribution, for
    a finishing prefill chunk the first-token distribution, and for a
    mid-prompt chunk they are computed-and-ignored (the planner only
    samples emitting rows). Pad sequences (q_len 0) alias a neighbour's
    last row; their logits are discarded host-side."""
    h, new_cache = ragged_forward(
        params, tokens, positions, slot_mapping, block_tables, cu_q_lens,
        context_lens, cache, config, block_size=block_size,
        max_q_len=max_q_len, attn_impl=attn_impl, lora=lora,
    )
    T = tokens.shape[0]
    last = jnp.clip(cu_q_lens[1:] - 1, 0, T - 1)  # [B]
    return _lm_head(params, h[last], config), new_cache


def verify_tokens_ragged(
    params: Params,
    tokens: jax.Array,       # [T] packed (current token + draft) rows
    positions: jax.Array,    # [T]
    slot_mapping: jax.Array, # [T]
    block_tables: jax.Array, # [B, MB]
    cu_q_lens: jax.Array,    # [B+1]
    context_lens: jax.Array, # [B]
    gather_idx: jax.Array,   # [B, K+1] packed row index per draft position
    cache: Cache,
    config: LlamaConfig,
    *,
    block_size: int,
    max_q_len: int,
    attn_impl: str = "auto",
    lora: "dict | None" = None,
) -> tuple[jax.Array, Cache]:
    """Ragged speculative verification -> (logits [B, K+1, V], cache).

    The packed replacement for `verify_tokens`: each row contributes
    exactly 1 + draft_len tokens instead of a [B, K+1] rectangle padded
    with trash-slot columns — the per-row bucket waste the ragged path
    deletes. `gather_idx[b, j]` maps draft position j back to its
    packed row (hosts clamp it to the row's last token for positions
    past the row's draft; `accept_draft` masks those by draft_lens, so
    duplicated logits are never consumed)."""
    h, new_cache = ragged_forward(
        params, tokens, positions, slot_mapping, block_tables, cu_q_lens,
        context_lens, cache, config, block_size=block_size,
        max_q_len=max_q_len, attn_impl=attn_impl, lora=lora,
    )
    return _lm_head(params, h[gather_idx], config), new_cache


def decode_step(
    params: Params,
    tokens: jax.Array,       # [B] int32 current tokens
    positions: jax.Array,    # [B] absolute positions
    slot_mapping: jax.Array, # [B] slot for the new K/V
    block_tables: jax.Array, # [B, MB]
    context_lens: jax.Array, # [B] length INCLUDING current token
    cache: Cache,
    config: LlamaConfig,
    *,
    block_size: int,
    attn_impl: str = "auto",
    lora: "dict | None" = None,
) -> tuple[jax.Array, Cache]:
    """One decode step for the running batch -> (logits [B, V], cache)."""
    c = config
    B = tokens.shape[0]
    cos, sin = rope_frequencies(c.head_dim, c.max_seq, c.rope_theta)
    h = params["embed"].astype(c.dtype)[tokens][:, None]  # [B, 1, D]
    pos2 = positions[:, None]  # [B, 1]

    lora_ids = lora["ids"] if lora is not None else None
    lora_stacks = (
        {k_: v_ for k_, v_ in lora.items() if k_ != "ids"} if lora is not None else None
    )

    def layer_step(carry, xs):
        h, = carry
        if lora_stacks is not None:
            lp, k_cache_l, v_cache_l, lora_l = xs
        else:
            lp, k_cache_l, v_cache_l = xs
        x = rms_norm(h, lp["ln1"], c.rms_eps)
        q, k, v = _qkv(x, lp, c)
        if lora_stacks is not None:
            q, k, v = _apply_lora(q, k, v, x, lora_l, lora_ids, c)
        q = apply_rope(q, cos, sin, pos2)
        k = apply_rope(k, cos, sin, pos2)
        k_cache_l = k_cache_l.at[:, slot_mapping].set(
            k[:, 0].swapaxes(0, 1).astype(k_cache_l.dtype)
        )
        v_cache_l = v_cache_l.at[:, slot_mapping].set(
            v[:, 0].swapaxes(0, 1).astype(v_cache_l.dtype)
        )
        o = paged_attention(
            q[:, 0],
            k_cache_l,
            v_cache_l,
            block_tables,
            context_lens,
            block_size=block_size,
            impl=attn_impl,
        )[:, None]  # [B, 1, H*D grouped]
        h = h + _out_proj(o, lp, B, 1, c)
        x = rms_norm(h, lp["ln2"], c.rms_eps)
        h = h + swiglu(x, lp["w_gate"], lp["w_up"], lp["w_down"])
        return (h,), (k_cache_l, v_cache_l)

    xs = (params["layers"], cache["k"], cache["v"])
    if lora_stacks is not None:
        xs = xs + (lora_stacks,)
    (h,), (new_k, new_v) = jax.lax.scan(layer_step, (h,), xs)
    h = rms_norm(h[:, 0], params["final_norm"], c.rms_eps)  # [B, D]
    return _lm_head(params, h, c), {"k": new_k, "v": new_v}
