"""Small MLP classifier (MNIST-class model for trainer tests/benchmarks;
the reference's analogous role is the torch_fashion_mnist example family
used by Train docs/tests)."""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from ray_tpu.nn.layers import init_dense


@dataclasses.dataclass(frozen=True)
class MlpConfig:
    in_dim: int = 784
    hidden: int = 256
    n_layers: int = 2
    n_classes: int = 10
    dtype: Any = jnp.float32


def logical_axes(config: MlpConfig) -> dict:
    axes = {"out": ("embed", None)}
    for i in range(config.n_layers):
        axes[f"w{i}"] = ("embed", "mlp")
        axes[f"b{i}"] = ("mlp",)
    return axes


def init_params(config: MlpConfig, key: jax.Array) -> dict:
    params = {}
    dim = config.in_dim
    for i in range(config.n_layers):
        key, sub = jax.random.split(key)
        params[f"w{i}"] = init_dense(sub, (dim, config.hidden), config.dtype)
        params[f"b{i}"] = jnp.zeros((config.hidden,), config.dtype)
        dim = config.hidden
    key, sub = jax.random.split(key)
    params["out"] = init_dense(sub, (dim, config.n_classes), config.dtype)
    return params


def forward(params: dict, x: jax.Array, config: MlpConfig) -> jax.Array:
    h = x
    for i in range(config.n_layers):
        h = jax.nn.relu(h @ params[f"w{i}"] + params[f"b{i}"])
    return h @ params["out"]


def loss_fn(params: dict, batch: dict, config: MlpConfig) -> jax.Array:
    logits = forward(params, batch["x"], config)
    labels = jax.nn.one_hot(batch["y"], config.n_classes)
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.sum(labels * logp, axis=-1))


def accuracy(params: dict, batch: dict, config: MlpConfig) -> jax.Array:
    logits = forward(params, batch["x"], config)
    return jnp.mean((jnp.argmax(logits, -1) == batch["y"]).astype(jnp.float32))
