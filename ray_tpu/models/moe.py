"""Mixture-of-Experts decoder (Mixtral-style) with expert parallelism.

The reference has NO expert parallelism (SURVEY.md §2.4 — absent from
python/ray/llm); this is a native capability. Design: Switch/GShard-style
capacity-bucketed dispatch expressed as einsums over an explicit expert
axis — the expert dimension carries the logical axis "expert" which the
sharding rules map to the mesh `ep` axis, so under pjit XLA lowers the
dispatch/combine einsums to all-to-alls over ICI (no hand-written
collectives; same rules table as DP/FSDP/TP/SP — parallel/sharding.py).

Attention/norms/embeddings reuse the llama block structure
(models/llama.py); only the FFN is replaced by the MoE layer.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp

from ray_tpu.models import llama
from ray_tpu.nn.layers import (
    apply_rope,
    cross_entropy_loss,
    init_dense,
    rms_norm,
    rope_frequencies,
)
from ray_tpu.ops.attention import attention

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class MoEConfig(llama.LlamaConfig):
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    router_aux_coeff: float = 0.01  # load-balancing loss weight

    def flops_per_token(self) -> float:
        d, f, L = self.d_model, self.d_ff, self.n_layers
        hd = self.head_dim
        attn = 2 * d * (self.n_heads * hd + 2 * self.n_kv_heads * hd + self.n_heads * hd)
        # only top_k experts run per token
        mlp = 2 * d * f * 3 * self.top_k
        emb = 2 * d * self.vocab_size
        return L * (attn + mlp) + emb

    def num_params(self) -> int:
        d, f, L, V, E = self.d_model, self.d_ff, self.n_layers, self.vocab_size, self.n_experts
        hd = self.head_dim
        per_layer = (
            d * hd * (self.n_heads * 2 + self.n_kv_heads * 2)
            + E * 3 * d * f  # experts
            + d * E          # router
            + 2 * d
        )
        head = 0 if self.tie_embeddings else d * V
        return V * d + L * per_layer + d + head


MOE_TINY = MoEConfig(
    vocab_size=512, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2, d_ff=128,
    max_seq=128, remat=False, n_experts=4, top_k=2,
)
MIXTRAL_8X7B = MoEConfig(
    vocab_size=32000, d_model=4096, n_layers=32, n_heads=32, n_kv_heads=8,
    d_ff=14336, max_seq=32768, rope_theta=1e6, n_experts=8, top_k=2,
)


def logical_axes(config: MoEConfig) -> Params:
    layer = {
        "ln1": ("layers", "norm"),
        "wq": ("layers", "embed", "heads"),
        "wk": ("layers", "embed", "kv_heads"),
        "wv": ("layers", "embed", "kv_heads"),
        "wo": ("layers", "heads", "embed"),
        "ln2": ("layers", "norm"),
        "router": ("layers", "embed", "expert"),
        "w_gate": ("layers", "expert", "embed", "mlp"),
        "w_up": ("layers", "expert", "embed", "mlp"),
        "w_down": ("layers", "expert", "mlp", "embed"),
    }
    axes: Params = {
        "embed": ("vocab", "embed"),
        "layers": layer,
        "final_norm": ("norm",),
    }
    if not config.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


def init_params(config: MoEConfig, key: jax.Array) -> Params:
    c = config
    keys = jax.random.split(key, 10)
    hd, L, E = c.head_dim, c.n_layers, c.n_experts

    def dense(k, shape):
        ks = jax.random.split(k, L)
        return jax.vmap(lambda kk: init_dense(kk, shape, c.param_dtype))(ks)

    def expert_dense(k, shape):
        # distinct init per (layer, expert)
        ks = jax.random.split(k, L * E).reshape(L, E)
        return jax.vmap(
            jax.vmap(lambda kk: init_dense(kk, shape, c.param_dtype))
        )(ks)

    params: Params = {
        "embed": init_dense(keys[0], (c.vocab_size, c.d_model), c.param_dtype, scale=1.0),
        "layers": {
            "ln1": jnp.ones((L, c.d_model), c.param_dtype),
            "wq": dense(keys[1], (c.d_model, c.n_heads * hd)),
            "wk": dense(keys[2], (c.d_model, c.n_kv_heads * hd)),
            "wv": dense(keys[3], (c.d_model, c.n_kv_heads * hd)),
            "wo": dense(keys[4], (c.n_heads * hd, c.d_model)),
            "ln2": jnp.ones((L, c.d_model), c.param_dtype),
            "router": dense(keys[5], (c.d_model, E)),
            "w_gate": expert_dense(keys[6], (c.d_model, c.d_ff)),
            "w_up": expert_dense(keys[7], (c.d_model, c.d_ff)),
            "w_down": expert_dense(keys[8], (c.d_ff, c.d_model)),
        },
        "final_norm": jnp.ones((c.d_model,), c.param_dtype),
    }
    if not c.tie_embeddings:
        params["lm_head"] = init_dense(
            keys[9], (c.d_model, c.vocab_size), c.param_dtype
        )
    return params


def moe_ffn(x: jax.Array, lp: Params, c: MoEConfig) -> tuple[jax.Array, jax.Array]:
    """Capacity-bucketed top-k MoE FFN.

    x: [B, S, D] -> (out [B, S, D], aux_loss scalar).
    Dispatch/combine are einsums with an explicit expert dim — sharded
    over `ep` by the rules table, XLA inserts the all-to-alls.
    """
    B, S, D = x.shape
    E, K = c.n_experts, c.top_k
    N = B * S
    C = max(1, int(c.capacity_factor * N * K / E))  # tokens per expert

    xt = x.reshape(N, D)
    logits = jnp.einsum("nd,de->ne", xt.astype(jnp.float32), lp["router"].astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)  # [N, E]

    # top-k expert choice per token
    gate_vals, gate_idx = jax.lax.top_k(probs, K)  # [N, K]
    gate_vals = gate_vals / (gate_vals.sum(-1, keepdims=True) + 1e-9)

    # position of each (token, k) within its expert's capacity bucket
    onehot = jax.nn.one_hot(gate_idx, E, dtype=jnp.int32)        # [N, K, E]
    flatoh = onehot.reshape(N * K, E)
    pos_in_expert = (jnp.cumsum(flatoh, axis=0) - flatoh).reshape(N, K, E)
    pos = (pos_in_expert * onehot).sum(-1)                        # [N, K]
    kept = (pos < C) & (gate_vals > 0)                            # [N, K]

    # dispatch tensor [N, E, C]: token n -> slot (e, c)
    disp = (
        jax.nn.one_hot(gate_idx, E, dtype=x.dtype)[..., None]
        * jax.nn.one_hot(jnp.where(kept, pos, C), C + 1, dtype=x.dtype)[..., :C][:, :, None, :]
    ).sum(1)  # [N, E, C]

    # expert inputs [E, C, D]
    xe = jnp.einsum("nec,nd->ecd", disp, xt)

    # expert FFN (swiglu), batched over E: [E, C, D] x [E, D, F]
    gate = jnp.einsum("ecd,edf->ecf", xe, lp["w_gate"].astype(x.dtype))
    up = jnp.einsum("ecd,edf->ecf", xe, lp["w_up"].astype(x.dtype))
    ye = jnp.einsum("ecf,efd->ecd", jax.nn.silu(gate) * up, lp["w_down"].astype(x.dtype))

    # combine weighted by gates: weight for slot (n,e,c) = disp * gate_val
    gate_per_ne = (
        jax.nn.one_hot(gate_idx, E, dtype=x.dtype) * (gate_vals * kept).astype(x.dtype)[..., None]
    ).sum(1)  # [N, E]
    comb = disp * gate_per_ne[:, :, None]  # [N, E, C]
    out = jnp.einsum("nec,ecd->nd", comb, ye)

    # load-balancing aux loss (Switch): E * sum_e f_e * p_e
    frac_tokens = (
        jax.nn.one_hot(gate_idx[:, 0], E, dtype=jnp.float32).mean(0)
    )
    mean_probs = probs.mean(0)
    aux = c.n_experts * jnp.sum(frac_tokens * mean_probs)
    return out.reshape(B, S, D), aux.astype(jnp.float32)


def _block(h, lp, *, config: MoEConfig, cos, sin, positions, segment_ids):
    c = config
    B, S, D = h.shape
    hd = c.head_dim
    x = rms_norm(h, lp["ln1"], c.rms_eps)
    q = jnp.einsum("bsd,dh->bsh", x, lp["wq"].astype(x.dtype)).reshape(B, S, c.n_heads, hd)
    k = jnp.einsum("bsd,dh->bsh", x, lp["wk"].astype(x.dtype)).reshape(B, S, c.n_kv_heads, hd)
    v = jnp.einsum("bsd,dh->bsh", x, lp["wv"].astype(x.dtype)).reshape(B, S, c.n_kv_heads, hd)
    q = apply_rope(q, cos, sin, positions)
    k = apply_rope(k, cos, sin, positions)
    o = attention(q, k, v, causal=True, segment_ids=segment_ids, impl=c.attention_impl)
    o = jnp.einsum("bsh,hd->bsd", o.reshape(B, S, c.n_heads * hd), lp["wo"].astype(x.dtype))
    h = h + o
    x = rms_norm(h, lp["ln2"], c.rms_eps)
    y, aux = moe_ffn(x, lp, c)
    return h + y, aux


def forward(
    params: Params,
    tokens: jax.Array,
    config: MoEConfig,
    *,
    positions: Optional[jax.Array] = None,
    segment_ids: Optional[jax.Array] = None,
) -> tuple[jax.Array, jax.Array]:
    """-> (logits [B, S, V], total aux loss)."""
    c = config
    B, S = tokens.shape
    if S > c.max_seq:
        raise ValueError(f"sequence length {S} > max_seq={c.max_seq}")
    if positions is None:
        positions = llama.packed_positions(segment_ids, S)
    cos, sin = rope_frequencies(c.head_dim, c.max_seq, c.rope_theta)
    h = params["embed"].astype(c.dtype)[tokens]

    block = partial(
        _block, config=c, cos=cos, sin=sin, positions=positions, segment_ids=segment_ids
    )
    if c.remat:
        block = jax.checkpoint(block)

    def scan_fn(carry, lp):
        h, aux = carry
        h, a = block(h, lp)
        return (h, aux + a), None

    (h, aux), _ = jax.lax.scan(scan_fn, (h, jnp.float32(0.0)), params["layers"])
    h = rms_norm(h, params["final_norm"], c.rms_eps)
    w_out = params.get("lm_head", None)
    if w_out is None:
        w_out = params["embed"].T
    logits = jnp.einsum("bsd,dv->bsv", h, w_out.astype(c.dtype))
    return logits, aux


def loss_fn(params: Params, batch: dict, config: MoEConfig) -> jax.Array:
    logits, aux = forward(
        params, batch["tokens"], config, segment_ids=batch.get("segment_ids")
    )
    ce, _ = cross_entropy_loss(logits, batch["targets"], batch.get("mask"))
    return ce + config.router_aux_coeff * aux
