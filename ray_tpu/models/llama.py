"""Llama-family decoder, TPU-first.

Design (vs reference, which delegates all model execution to
torch/vLLM inside workers — e.g. python/ray/llm/_internal/serve/
deployments/llm/vllm/vllm_engine.py): pure-functional jax with

  * stacked layer params + `lax.scan` over layers (one compiled block,
    fast compiles, pipeline-parallel ready: the "layers" dim reshapes to
    ("stage", "layers_per_stage") and shards over the mesh `pp` axis),
  * logical-axis annotations on every tensor (ray_tpu.parallel.sharding)
    so DP/FSDP/TP/SP all come from the rules table, not model edits,
  * bf16 compute / fp32 params+norms, fp32 softmax and loss,
  * per-layer rematerialization (`jax.checkpoint`) to trade MXU FLOPs
    for HBM.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional

import jax
import jax.ad_checkpoint
import jax.numpy as jnp

from ray_tpu.nn.layers import (
    apply_rope,
    cross_entropy_loss,
    fused_cross_entropy_loss,
    init_dense,
    rms_norm,
    rope_frequencies,
    swiglu,
)
from ray_tpu.ops.attention import attention

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 14336
    max_seq: int = 8192
    rope_theta: float = 500000.0
    rms_eps: float = 1e-5
    dtype: Any = jnp.bfloat16  # compute/activation dtype
    param_dtype: Any = jnp.float32
    remat: bool = True
    # remat granularity: "full" recomputes the whole block in backward
    # (max memory savings, ~1 extra forward of MXU work); "dots" saves
    # matmul outputs and recomputes only elementwise/attention-score work
    # (jax.checkpoint_policies.dots_with_no_batch_dims_saveable) — near
    # no-remat throughput at a fraction of full-activation memory.
    remat_policy: str = "dots"
    attention_impl: str = "xla"
    tie_embeddings: bool = False

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def flops_per_token(self) -> float:
        """Approximate forward matmul FLOPs per token (2*params-style count)."""
        d, f, L = self.d_model, self.d_ff, self.n_layers
        hd = self.head_dim
        attn_proj = 2 * d * (self.n_heads * hd + 2 * self.n_kv_heads * hd + self.n_heads * hd)
        mlp = 2 * d * f * 3
        emb = 2 * d * self.vocab_size
        return L * (attn_proj + mlp) + emb

    def num_params(self) -> int:
        d, f, L, V = self.d_model, self.d_ff, self.n_layers, self.vocab_size
        hd = self.head_dim
        per_layer = d * hd * (self.n_heads * 2 + self.n_kv_heads * 2) + 3 * d * f + 2 * d
        head = 0 if self.tie_embeddings else d * V
        return V * d + L * per_layer + d + head


LLAMA3_8B = LlamaConfig()
LLAMA3_1B = LlamaConfig(
    d_model=2048, n_layers=16, n_heads=32, n_kv_heads=8, d_ff=8192, tie_embeddings=True
)
LLAMA_400M = LlamaConfig(
    vocab_size=32000, d_model=1024, n_layers=24, n_heads=16, n_kv_heads=8, d_ff=2816,
    max_seq=2048,
)
LLAMA_TINY = LlamaConfig(
    vocab_size=512, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2, d_ff=128,
    max_seq=128, remat=False,
)


def logical_axes(config: LlamaConfig) -> Params:
    """Pytree (parallel to params) of logical-axis tuples."""
    layer = {
        "ln1": ("layers", "norm"),
        "wq": ("layers", "embed", "heads"),
        "wk": ("layers", "embed", "kv_heads"),
        "wv": ("layers", "embed", "kv_heads"),
        "wo": ("layers", "heads", "embed"),
        "ln2": ("layers", "norm"),
        "w_gate": ("layers", "embed", "mlp"),
        "w_up": ("layers", "embed", "mlp"),
        "w_down": ("layers", "mlp", "embed"),
    }
    axes: Params = {
        "embed": ("vocab", "embed"),
        "layers": layer,
        "final_norm": ("norm",),
    }
    if not config.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


def init_params(config: LlamaConfig, key: jax.Array) -> Params:
    c = config
    keys = jax.random.split(key, 8)
    hd = c.head_dim
    L = c.n_layers

    def dense(k, shape):
        # init per-layer with distinct keys folded over the layer axis
        ks = jax.random.split(k, L)
        return jax.vmap(lambda kk: init_dense(kk, shape, c.param_dtype))(ks)

    params: Params = {
        "embed": init_dense(keys[0], (c.vocab_size, c.d_model), c.param_dtype, scale=1.0),
        "layers": {
            "ln1": jnp.ones((L, c.d_model), c.param_dtype),
            "wq": dense(keys[1], (c.d_model, c.n_heads * hd)),
            "wk": dense(keys[2], (c.d_model, c.n_kv_heads * hd)),
            "wv": dense(keys[3], (c.d_model, c.n_kv_heads * hd)),
            "wo": dense(keys[4], (c.n_heads * hd, c.d_model)),
            "ln2": jnp.ones((L, c.d_model), c.param_dtype),
            "w_gate": dense(keys[5], (c.d_model, c.d_ff)),
            "w_up": dense(keys[6], (c.d_model, c.d_ff)),
            "w_down": dense(keys[7], (c.d_ff, c.d_model)),
        },
        "final_norm": jnp.ones((c.d_model,), c.param_dtype),
    }
    if not c.tie_embeddings:
        params["lm_head"] = init_dense(
            jax.random.fold_in(key, 99), (c.d_model, c.vocab_size), c.param_dtype
        )
    return params


def packed_positions(segment_ids: Optional[jax.Array], seq_len: int) -> jax.Array:
    """RoPE positions: arange normally; restart at 0 per segment when packing."""
    if segment_ids is None:
        return jnp.arange(seq_len, dtype=jnp.int32)
    idx = jnp.arange(seq_len, dtype=jnp.int32)[None, :]  # [1, S]
    changed = jnp.concatenate(
        [
            jnp.zeros_like(segment_ids[:, :1], dtype=bool),
            segment_ids[:, 1:] != segment_ids[:, :-1],
        ],
        axis=1,
    )
    seg_start = jax.lax.cummax(jnp.where(changed, idx, 0), axis=1)  # [B, S]
    return idx - seg_start


def _block(
    h: jax.Array,  # [B, S, D]
    lp: Params,  # one layer's params (no leading layer dim)
    *,
    config: LlamaConfig,
    cos: jax.Array,
    sin: jax.Array,
    positions: jax.Array,
    segment_ids: Optional[jax.Array],
) -> jax.Array:
    c = config
    B, S, D = h.shape
    hd = c.head_dim

    x = rms_norm(h, lp["ln1"], c.rms_eps)
    q = jnp.einsum("bsd,dh->bsh", x, lp["wq"].astype(x.dtype)).reshape(B, S, c.n_heads, hd)
    k = jnp.einsum("bsd,dh->bsh", x, lp["wk"].astype(x.dtype)).reshape(B, S, c.n_kv_heads, hd)
    v = jnp.einsum("bsd,dh->bsh", x, lp["wv"].astype(x.dtype)).reshape(B, S, c.n_kv_heads, hd)
    q = apply_rope(q, cos, sin, positions)
    k = apply_rope(k, cos, sin, positions)
    o = attention(q, k, v, causal=True, segment_ids=segment_ids, impl=c.attention_impl)
    # named so the "dots" remat policy can SAVE it: the policy recognizes
    # dot_general outputs but not a pallas_call's, so without the name the
    # backward pass re-runs the whole flash kernel forward (~25% of a
    # train step) just to rebuild this tensor
    o = jax.ad_checkpoint.checkpoint_name(o, "attn_out")
    o = jnp.einsum("bsh,hd->bsd", o.reshape(B, S, c.n_heads * hd), lp["wo"].astype(x.dtype))
    h = h + o

    x = rms_norm(h, lp["ln2"], c.rms_eps)
    return h + swiglu(x, lp["w_gate"], lp["w_up"], lp["w_down"])


def hidden_states(
    params: Params,
    tokens: jax.Array,  # [B, S] int32
    config: LlamaConfig,
    *,
    positions: Optional[jax.Array] = None,
    segment_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """Full-sequence forward up to the final norm -> h [B, S, D].

    The training loss pairs this with nn.layers.fused_cross_entropy_loss
    so the [T, V] logits never exist as a stored fp32 tensor; serving
    keeps using forward() -> logits."""
    c = config
    B, S = tokens.shape
    if S > c.max_seq:
        raise ValueError(
            f"sequence length {S} exceeds config.max_seq={c.max_seq}; the RoPE "
            "table would silently clamp (JAX OOB gather) — raise max_seq instead"
        )
    if positions is None:
        positions = packed_positions(segment_ids, S)
    cos, sin = rope_frequencies(c.head_dim, c.max_seq, c.rope_theta)

    h = params["embed"].astype(c.dtype)[tokens]  # [B, S, D]

    block = partial(
        _block, config=c, cos=cos, sin=sin, positions=positions, segment_ids=segment_ids
    )
    if c.remat:
        if c.remat_policy == "dots":
            block = jax.checkpoint(
                block,
                policy=jax.checkpoint_policies.save_from_both_policies(
                    jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
                    jax.checkpoint_policies.save_only_these_names(
                        "attn_out", "attn_lse"
                    ),
                ),
            )
        elif c.remat_policy == "full":
            block = jax.checkpoint(block)
        else:
            raise ValueError(
                f"unknown remat_policy {c.remat_policy!r}; 'full' or 'dots'"
            )

    from ray_tpu.parallel.context import current_mesh

    mesh = current_mesh()
    pp = mesh.shape.get("pp", 1) if mesh is not None else 1
    if pp > 1:
        # pipeline the layer stack over the mesh `pp` axis (GPipe
        # microbatch schedule inside this jitted program — see
        # parallel/pipeline.py; reference PP is external vLLM stage
        # actors, vllm_models.py:121)
        if segment_ids is not None:
            raise NotImplementedError("segment packing + pipeline parallelism")
        if positions.ndim > 1:
            # per-batch positions would need microbatching alongside h
            raise NotImplementedError("batched positions + pipeline parallelism")
        from ray_tpu.parallel.pipeline import pipeline_apply, stack_stages

        def stage(stage_params, x):
            out, _ = jax.lax.scan(
                lambda carry, lp: (block(carry, lp), None), x, stage_params
            )
            return out

        h = pipeline_apply(
            mesh, stage, stack_stages(params["layers"], pp), h, n_micro=pp
        )
    else:
        h, _ = jax.lax.scan(lambda carry, lp: (block(carry, lp), None), h, params["layers"])

    return rms_norm(h, params["final_norm"], c.rms_eps)


def output_weight(params: Params) -> jax.Array:
    """[D, V] lm-head weight (tied embedding transpose when untied absent)."""
    w_out = params.get("lm_head", None)
    if w_out is None:
        w_out = params["embed"].T
    return w_out


def forward(
    params: Params,
    tokens: jax.Array,  # [B, S] int32
    config: LlamaConfig,
    *,
    positions: Optional[jax.Array] = None,
    segment_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """Full-sequence forward -> logits [B, S, V] (loss-dtype fp32 left to caller)."""
    h = hidden_states(
        params, tokens, config, positions=positions, segment_ids=segment_ids
    )
    w_out = output_weight(params)
    return jnp.einsum("bsd,dv->bsv", h, w_out.astype(config.dtype))


def loss_fn(
    params: Params,
    batch: dict[str, jax.Array],  # tokens [B,S], targets [B,S], optional mask [B,S]
    config: LlamaConfig,
) -> jax.Array:
    loss, _ = loss_and_weight_fn(params, batch, config)
    return loss


def loss_and_weight_fn(
    params: Params,
    batch: dict[str, jax.Array],
    config: LlamaConfig,
) -> tuple[jax.Array, jax.Array]:
    """(mean_loss, valid_token_count) — the weighted form grad-accum needs.

    Uses the fused lm-head + CE (nn/layers.py fused_cross_entropy_loss):
    the [T, V] fp32 logits/softmax pipeline was ~36% of the flagship
    train step before fusion (round-5 profile)."""
    import os

    # A/B probe hook (benchmarks). Read at TRACE time: flipping it in a
    # process that already compiled the step has no effect — set it in a
    # fresh process (the benchmark harnesses fork per variant).
    if os.environ.get("RAY_TPU_NAIVE_CE"):
        logits = forward(
            params, batch["tokens"], config,
            segment_ids=batch.get("segment_ids"),
        )
        return cross_entropy_loss(logits, batch["targets"], batch.get("mask"))
    h = hidden_states(
        params, batch["tokens"], config, segment_ids=batch.get("segment_ids")
    )
    return fused_cross_entropy_loss(
        h, output_weight(params), batch["targets"], batch.get("mask")
    )
