"""ray_tpu.obs.perfwatch — continuous performance observability.

Three legs:

 * **Capture ledger + regression gates** (ledger.py, migrate.py,
   ray_tpu/analysis/perf_gate.py): every bench capture carries one
   additive envelope — schema version, hardware fingerprint, metric
   dict with tolerance bands — and ``scripts/check_perf.py`` gates
   fresh captures against the most recent same-fingerprint baseline.
 * **Always-on sampled profiling** (sampler.py, metrics.py): a
   low-duty-cycle ``PerfSampler`` re-runs the chained-probe ladders on
   live trainer/engine state and exports ``ray_tpu_perf_*`` telemetry
   series graded through the SLO machinery.
 * **The roadmap's probes**: the profiler's backward split
   (ce_bwd / mlp_bwd / attention_bwd) + allreduce-overlap probe live in
   ray_tpu/profiler/segments.py; GCS lock/RPC histograms in
   ray_tpu/cluster/lockstats.py.
"""

from __future__ import annotations

from typing import Optional

from ray_tpu.obs.perfwatch.ledger import (
    CaptureLedger,
    MetricSpec,
    current_fingerprint,
    envelope_of,
    fingerprints_match,
    load_capture,
    metric,
    payload_of,
    validate_envelope,
    wrap,
    write_capture,
)
from ray_tpu.obs.perfwatch.sampler import PerfSampler

__all__ = [
    "CaptureLedger",
    "MetricSpec",
    "current_fingerprint",
    "envelope_of",
    "fingerprints_match",
    "load_capture",
    "metric",
    "payload_of",
    "PerfSampler",
    "save_capture",
    "validate_envelope",
    "wrap",
    "write_capture",
]


def save_capture(path: str, payload: dict, *,
                 metrics: Optional[dict] = None,
                 fingerprint: Optional[dict] = None) -> str:
    """The one-call writer the bench scripts use in place of their old
    ``json.dump``: derives the bench family + revision from the
    filename, derives comparable metrics from the payload's shape (same
    derivation the migration applied to the legacy captures, so fresh
    captures stay comparable to their migrated baselines), stamps the
    current backend's fingerprint (wildcard when no backend is up), and
    writes the enveloped capture."""
    from ray_tpu.obs.perfwatch.migrate import (
        bench_rev_from_name,
        derive_metrics,
    )

    bench, rev = bench_rev_from_name(path)
    if metrics is None:
        metrics = derive_metrics(payload)
    return write_capture(path, payload, bench=bench, rev=rev,
                         metrics=metrics, fingerprint=fingerprint)
