"""One-shot (idempotent) migration of pre-envelope captures into the
capture ledger.

Two jobs:

 * wrap every ``benchmarks/*.json`` capture in the perfwatch envelope
   IN PLACE (additive — payload keys survive, existing readers keep
   working), deriving comparable metrics + a best-effort hardware
   fingerprint from each known legacy shape;
 * end the capture-location split: root-level ``BENCH_r*.json`` /
   ``PERF_r*.json`` / ``MULTICHIP_r*.json`` move into ``benchmarks/``
   (enveloped), with a symlink left at the old root path so any reader
   of the old location keeps working.

Run: ``python -m ray_tpu.obs.perfwatch.migrate`` (safe to re-run: files
already carrying an envelope, and root paths already symlinks, are
skipped).
"""

from __future__ import annotations

import json
import os
import re
import time
from typing import Optional

from ray_tpu.obs.perfwatch.ledger import (
    ENVELOPE_KEY,
    envelope_of,
    metric,
    wrap,
)

_REV_RE = re.compile(r"^(?P<bench>.+?)_(?P<rev>r\d+)$")

# root-level captures that move under benchmarks/ (satellite: end the
# two-directory split)
_ROOT_CAPTURE_RE = re.compile(r"^(BENCH|PERF|MULTICHIP)_r\d+\.json$")

# tolerance bands by metric character: wall-clock numbers on a loaded
# shared-CPU runner swing hard, ratios and coverages don't
REL_TIME = 1.0
REL_THROUGHPUT = 0.6
REL_RATIO = 0.25
REL_COVERAGE = 0.08


def bench_rev_from_name(filename: str) -> tuple[str, str]:
    stem = os.path.splitext(os.path.basename(filename))[0]
    m = _REV_RE.match(stem)
    if m:
        return m.group("bench"), m.group("rev")
    return stem, "r00"


def _iso(ts: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(ts))


def fingerprint_from_payload(payload: dict) -> dict:
    """Best-effort fingerprint for a capture that predates the envelope.
    Unknown fields stay None — a WILDCARD, never a guess (a fabricated
    jax version would manufacture comparability that doesn't exist)."""
    parsed = payload.get("parsed")
    nested = parsed if isinstance(parsed, dict) else {}
    device_kind = (payload.get("device_kind") or payload.get("device")
                   or nested.get("device"))
    platform = payload.get("platform") or nested.get("platform")
    if platform is None and isinstance(device_kind, str):
        low = device_kind.lower()
        if "tpu" in low or low.startswith("v5") or low.startswith("v6"):
            platform = "tpu"
        elif low == "cpu":
            platform = "cpu"
    count = payload.get("n_devices") or payload.get("num_devices")
    return {
        "device_kind": device_kind if isinstance(device_kind, str) else None,
        "platform": platform if isinstance(platform, str) else None,
        "device_count": count if isinstance(count, int) else None,
        "jax_version": None,
    }


def _gate_metrics(payload: dict) -> dict:
    """Boolean gates -> 0/1 metrics with a zero band: a gate that was
    green may never silently go red."""
    out = {}
    for key in ("gate", "gates"):
        gates = payload.get(key)
        if isinstance(gates, dict):
            for name, v in gates.items():
                if isinstance(v, bool):
                    out[f"gate_{name}"] = metric(
                        1.0 if v else 0.0, "bool", rel_tol=0.0)
    for key in ("token_identical", "all_gates_pass", "ok", "exact"):
        v = payload.get(key)
        if isinstance(v, bool):
            out[f"gate_{key}"] = metric(1.0 if v else 0.0, "bool", rel_tol=0.0)
    return out


def derive_metrics(payload: dict) -> dict:
    """Comparable numbers from a legacy capture's known shapes."""
    out: dict = {}

    # headline {metric, value, unit} records (SERVING, SPEC, KVTIER, ...)
    name = payload.get("metric")
    value = payload.get("value")
    if isinstance(name, str) and isinstance(value, (int, float)) \
            and not isinstance(value, bool):
        out[name] = metric(value, str(payload.get("unit", "")),
                           rel_tol=REL_THROUGHPUT)

    # profiler StepProfile captures
    if isinstance(payload.get("coverage_pct"), (int, float)):
        out["coverage_pct"] = metric(payload["coverage_pct"], "%",
                                     rel_tol=REL_COVERAGE)
    if isinstance(payload.get("measured_step_ms"), (int, float)):
        out["measured_step_ms"] = metric(payload["measured_step_ms"], "ms",
                                         better="lower", rel_tol=REL_TIME)

    # bench.py driver records ({n, cmd, rc, parsed:{...}})
    parsed = payload.get("parsed")
    if isinstance(parsed, dict) and isinstance(parsed.get("metric"), str) \
            and isinstance(parsed.get("value"), (int, float)):
        out[parsed["metric"]] = metric(
            parsed["value"], str(parsed.get("unit", "")),
            rel_tol=REL_RATIO)
        tps = parsed.get("tokens_per_sec")
        if isinstance(tps, (int, float)):
            out["tokens_per_sec"] = metric(tps, "tok/s",
                                           rel_tol=REL_THROUGHPUT)
    tps = payload.get("tokens_per_sec")
    if isinstance(tps, (int, float)) and not isinstance(tps, bool) \
            and "tokens_per_sec" not in out:
        out["tokens_per_sec"] = metric(tps, "tok/s", rel_tol=REL_THROUGHPUT)

    # microbenchmark suites ({name: {value, unit, ...}}, PERF_r*)
    for k, v in payload.items():
        if isinstance(v, dict) and isinstance(v.get("value"), (int, float)) \
                and isinstance(v.get("unit"), str) and k not in out \
                and k != "parsed":
            out[k] = metric(v["value"], v["unit"], rel_tol=REL_THROUGHPUT)

    # control-plane ingest (CONTROLPLANE_gcs_r20): batched ops/s at the
    # largest node count is THE number item 2's sharding will be graded on
    results = payload.get("results")
    if isinstance(results, list) and results \
            and all(isinstance(r, dict) and "nodes" in r for r in results):
        largest = max(results, key=lambda r: r.get("nodes", 0))
        for key, unit in (("batched_ops_per_s", "ops/s"),
                          ("unbatched_ops_per_s", "ops/s")):
            if isinstance(largest.get(key), (int, float)):
                out[f"{key}_at_{largest['nodes']}_nodes"] = metric(
                    largest[key], unit, rel_tol=REL_THROUGHPUT)

    out.update(_gate_metrics(payload))
    return out


def migrate_file(path: str) -> Optional[str]:
    """Envelope one capture file in place; returns an action string or
    None when the file already carries an envelope / isn't a capture."""
    with open(path) as f:
        payload = json.load(f)
    if not isinstance(payload, dict) or envelope_of(payload) is not None:
        return None
    bench, rev = bench_rev_from_name(path)
    ts = payload.get("ts")
    if isinstance(ts, str):
        captured_at = ts
    elif isinstance(payload.get("unix_time"), (int, float)):
        captured_at = _iso(payload["unix_time"])
    else:
        captured_at = _iso(os.path.getmtime(path))
    doc = wrap(
        payload, bench=bench, rev=rev, metrics=derive_metrics(payload),
        fingerprint=fingerprint_from_payload(payload),
        captured_at=captured_at,
    )
    with open(path, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    n = len(doc[ENVELOPE_KEY]["metrics"])
    return f"enveloped {path} (bench={bench} rev={rev}, {n} metrics)"


def migrate_root_captures(repo_root: str, bench_dir: str) -> list[str]:
    """Move root BENCH/PERF/MULTICHIP captures into benchmarks/ and leave
    symlink shims at the old paths."""
    actions = []
    for name in sorted(os.listdir(repo_root)):
        if not _ROOT_CAPTURE_RE.match(name):
            continue
        src = os.path.join(repo_root, name)
        dst = os.path.join(bench_dir, name)
        if os.path.islink(src):
            continue  # already migrated
        if os.path.exists(dst):
            actions.append(f"SKIP {src}: {dst} already exists")
            continue
        os.rename(src, dst)
        # relative symlink so the repo stays relocatable
        os.symlink(os.path.join("benchmarks", name), src)
        actions.append(f"moved {name} -> benchmarks/ (symlink shim at root)")
    return actions


def migrate_all(repo_root: Optional[str] = None) -> list[str]:
    if repo_root is None:
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))))
    bench_dir = os.path.join(repo_root, "benchmarks")
    actions = migrate_root_captures(repo_root, bench_dir)
    for name in sorted(os.listdir(bench_dir)):
        if not name.endswith(".json"):
            continue
        act = migrate_file(os.path.join(bench_dir, name))
        if act:
            actions.append(act)
    return actions


def main() -> int:
    actions = migrate_all()
    for a in actions:
        print(a)
    print(f"migrate: {len(actions)} action(s)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
