"""Capture ledger: one envelope for every checked-in bench capture.

Every `benchmarks/*_r*.json` capture historically had its own shape, no
hardware fingerprint, and only ad-hoc per-file tier-1 gates — so the
perf trajectory was unreadable by machines, and the "refresh every CPU
capture on the TPU" carry-over had no mechanical definition of *refresh*
(reference discipline: the MLPerf-on-TPU-pods capture format — every
number stamped with the hardware that produced it, comparable only to
its own kind).

The envelope is ADDITIVE: the original capture payload keeps its
top-level keys (every existing reader — tests, benches, humans — keeps
working) and gains ONE reserved key::

    {
      ...original payload...,
      "perfwatch": {
        "schema": 1,
        "bench": "profile_trainstep",      # capture family
        "rev": "r06",                      # capture revision
        "captured_at": "2026-08-07T00:00:00Z",
        "fingerprint": {                   # hardware identity; null = unknown
          "device_kind": "cpu", "platform": "cpu",
          "device_count": 1, "jax_version": "0.4.37",
        },
        "metrics": {                       # the machine-comparable numbers
          "coverage_pct": {"value": 97.4, "unit": "%",
                            "better": "higher", "rel_tol": 0.1},
        },
      },
    }

Comparability contract (ray_tpu/analysis/perf_gate.py enforces it):
captures compare ONLY against the most recent ledger entry of the same
bench family with a MATCHING fingerprint; a ``null`` fingerprint field
is a wildcard (legacy captures predate the envelope and recorded no jax
version). A fresh TPU capture therefore never fights a CPU baseline —
it records as the new baseline for its own fingerprint, which is
exactly how a TPU refresh supersedes a CPU number.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Callable, Optional

SCHEMA_VERSION = 1
ENVELOPE_KEY = "perfwatch"

FINGERPRINT_KEYS = ("device_kind", "platform", "device_count", "jax_version")

BETTER_HIGHER = "higher"
BETTER_LOWER = "lower"
VALID_BETTER = frozenset({BETTER_HIGHER, BETTER_LOWER})

# Default relative tolerance bands. Wall-clock numbers on a shared CPU
# runner are noisy (the tier-1 suite runs under load), so time-like
# metrics get a wide band; ratios/coverages are stable and get a tight
# one. Individual captures override per metric.
DEFAULT_REL_TOL = 0.5


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))


def default_ledger_dir() -> str:
    return os.path.join(_repo_root(), "benchmarks")


@dataclasses.dataclass
class MetricSpec:
    """One comparable number + its tolerance band."""

    value: float
    unit: str = ""
    better: str = BETTER_HIGHER
    rel_tol: float = DEFAULT_REL_TOL
    abs_tol: float = 0.0

    def to_dict(self) -> dict:
        return {
            "value": self.value, "unit": self.unit, "better": self.better,
            "rel_tol": self.rel_tol, "abs_tol": self.abs_tol,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "MetricSpec":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})


def metric(value, unit: str = "", better: str = BETTER_HIGHER,
           rel_tol: float = DEFAULT_REL_TOL, abs_tol: float = 0.0) -> dict:
    """Shorthand the bench writers use to declare one enveloped metric."""
    if better not in VALID_BETTER:
        raise ValueError(f"better must be one of {sorted(VALID_BETTER)}")
    return MetricSpec(float(value), unit, better, rel_tol, abs_tol).to_dict()


def current_fingerprint() -> dict:
    """Hardware fingerprint of THIS process's JAX backend.

    Importing jax here initializes a backend — only call from a process
    that is allowed to (bench children, never bench.py's parent)."""
    import jax

    dev = jax.devices()[0]
    return {
        "device_kind": getattr(dev, "device_kind", "") or dev.platform,
        "platform": dev.platform,
        "device_count": jax.device_count(),
        "jax_version": jax.__version__,
    }


def fingerprints_match(a: Optional[dict], b: Optional[dict]) -> bool:
    """Same-hardware test with null-as-wildcard: legacy captures recorded
    no jax version (the envelope postdates them), and an unknown field
    must not make every legacy baseline unreachable."""
    if not a or not b:
        return False
    for k in FINGERPRINT_KEYS:
        va, vb = a.get(k), b.get(k)
        if va is None or vb is None:
            continue
        if va != vb:
            return False
    return True


def envelope_of(doc: dict) -> Optional[dict]:
    env = doc.get(ENVELOPE_KEY) if isinstance(doc, dict) else None
    return env if isinstance(env, dict) else None


def payload_of(doc: dict) -> dict:
    """The original capture payload, envelope key stripped."""
    return {k: v for k, v in doc.items() if k != ENVELOPE_KEY}


def wrap(payload: dict, *, bench: str, rev: str, metrics: dict,
         fingerprint: Optional[dict] = None,
         captured_at: Optional[str] = None) -> dict:
    """Envelope a capture payload (additive: payload keys preserved)."""
    if not isinstance(payload, dict):
        raise TypeError(f"capture payload must be a dict, got {type(payload)}")
    fp = {k: (fingerprint or {}).get(k) for k in FINGERPRINT_KEYS}
    norm_metrics = {}
    for name, spec in (metrics or {}).items():
        if isinstance(spec, MetricSpec):
            spec = spec.to_dict()
        norm_metrics[name] = MetricSpec.from_dict(spec).to_dict()
    return {
        **payload_of(payload),
        ENVELOPE_KEY: {
            "schema": SCHEMA_VERSION,
            "bench": bench,
            "rev": rev,
            "captured_at": captured_at or time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "fingerprint": fp,
            "metrics": norm_metrics,
        },
    }


def validate_envelope(doc: dict) -> list[str]:
    """Schema problems of one enveloped capture (empty = valid)."""
    problems = []
    env = envelope_of(doc)
    if env is None:
        return ["no perfwatch envelope"]
    if env.get("schema") != SCHEMA_VERSION:
        problems.append(f"unknown envelope schema {env.get('schema')!r}")
    for field in ("bench", "rev", "captured_at"):
        if not isinstance(env.get(field), str) or not env.get(field):
            problems.append(f"envelope field {field!r} missing or not a string")
    fp = env.get("fingerprint")
    if not isinstance(fp, dict):
        problems.append("envelope fingerprint missing")
    else:
        for k in FINGERPRINT_KEYS:
            if k not in fp:
                problems.append(f"fingerprint missing key {k!r}")
    metrics = env.get("metrics")
    if not isinstance(metrics, dict):
        problems.append("envelope metrics missing (may be empty, not absent)")
    else:
        for name, spec in metrics.items():
            if not isinstance(spec, dict):
                problems.append(f"metric {name!r}: not a dict")
                continue
            v = spec.get("value")
            if not isinstance(v, (int, float)) or v != v:  # NaN check
                problems.append(f"metric {name!r}: non-numeric value {v!r}")
            if spec.get("better") not in VALID_BETTER:
                problems.append(
                    f"metric {name!r}: better={spec.get('better')!r} not in "
                    f"{sorted(VALID_BETTER)}"
                )
            for tol in ("rel_tol", "abs_tol"):
                t = spec.get(tol, 0)
                if not isinstance(t, (int, float)) or t < 0:
                    problems.append(f"metric {name!r}: invalid {tol}={t!r}")
    return problems


class CaptureLedger:
    """Reader/writer over the capture directory (default: benchmarks/).

    The ledger IS the directory: one enveloped JSON per capture, history
    in git. ``write`` envelopes + persists; ``entries``/``baseline_for``
    resolve comparison baselines by (bench family, fingerprint)."""

    def __init__(self, root: Optional[str] = None):
        self.root = root or default_ledger_dir()

    # -- writing --------------------------------------------------------------

    def write(self, name_or_path: str, payload: dict, *, bench: str,
              rev: str, metrics: dict,
              fingerprint: Optional[dict] = None) -> str:
        """Envelope + write a capture. ``name_or_path`` may be a bare
        filename (lands in the ledger root) or a full path (the bench's
        --out flag wins, wherever it points)."""
        path = (name_or_path if os.path.isabs(name_or_path)
                or os.sep in name_or_path
                else os.path.join(self.root, name_or_path))
        doc = wrap(payload, bench=bench, rev=rev, metrics=metrics,
                   fingerprint=fingerprint)
        with open(path, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        return path

    # -- reading --------------------------------------------------------------

    def entries(self, bench: Optional[str] = None) -> list[tuple[str, dict]]:
        """(path, doc) for every enveloped capture in the ledger,
        newest-first by captured_at. Un-enveloped JSONs are skipped here
        (check_perf flags them as migration gaps)."""
        out = []
        try:
            names = sorted(os.listdir(self.root))
        except OSError:
            return []
        for name in names:
            if not name.endswith(".json"):
                continue
            path = os.path.join(self.root, name)
            try:
                with open(path) as f:
                    doc = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue
            env = envelope_of(doc)
            if env is None:
                continue
            if bench is not None and env.get("bench") != bench:
                continue
            out.append((path, doc))
        out.sort(key=lambda pd: envelope_of(pd[1]).get("captured_at", ""),
                 reverse=True)
        return out

    def unenveloped(self) -> list[str]:
        """Capture files the migration has not covered (ledger-integrity
        problem list for check_perf)."""
        out = []
        try:
            names = sorted(os.listdir(self.root))
        except OSError:
            return []
        for name in names:
            if not name.endswith(".json"):
                continue
            path = os.path.join(self.root, name)
            try:
                with open(path) as f:
                    doc = json.load(f)
            except (OSError, json.JSONDecodeError):
                out.append(path)
                continue
            if not isinstance(doc, dict) or envelope_of(doc) is None:
                out.append(path)
        return out

    def baseline_for(self, bench: str, fingerprint: Optional[dict], *,
                     exclude: Optional[str] = None
                     ) -> Optional[tuple[str, dict]]:
        """Most recent same-fingerprint entry of ``bench`` — the capture
        a fresh run is gated against. ``exclude`` drops one path (the
        fresh capture itself when it already landed in the ledger)."""
        for path, doc in self.entries(bench):
            if exclude is not None and os.path.abspath(path) == os.path.abspath(exclude):
                continue
            if fingerprints_match(envelope_of(doc).get("fingerprint"),
                                  fingerprint):
                return path, doc
        return None


def load_capture(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def write_capture(path: str, payload: dict, *, bench: str, rev: str,
                  metrics: dict, fingerprint: Optional[dict] = None,
                  fingerprint_fn: Optional[Callable[[], dict]] = None) -> str:
    """Module-level convenience the bench scripts call in place of their
    old ``json.dump``: envelope + write to ``path``. ``fingerprint_fn``
    defaults to ``current_fingerprint`` guarded — a bench that never
    initialized a backend still writes a valid (wildcard) envelope."""
    if fingerprint is None:
        fn = fingerprint_fn or current_fingerprint
        try:
            fingerprint = fn()
        except Exception:  # noqa: BLE001 — no backend: wildcard fingerprint
            fingerprint = None
    return CaptureLedger(os.path.dirname(os.path.abspath(path))).write(
        path, payload, bench=bench, rev=rev, metrics=metrics,
        fingerprint=fingerprint,
    )
