"""Always-on sampled step profiling (perfwatch leg 2).

``PerfSampler`` re-runs the chained-probe ladders (profiler.segments)
against LIVE workloads — a trainer's config/params/batch, an engine's
weights via ``LLMEngine.profile_decode`` — on a background thread at a
low duty cycle, so segment-level perf is a continuously-updated
telemetry series instead of a stale bench artifact. Between captures,
`ray_tpu status` and the dashboard ``/api/perf`` route show where the
step time is going NOW.

Budget discipline: the sampler never holds the hot path (probes run on
their own thread against scratch state; ``profile_decode`` uses a
scratch KV cache) and its wall-clock share is bounded — after a probe
takes ``w`` seconds the next one waits at least ``w/max_duty - w``, so
the long-run duty cycle stays ≤ ``max_duty`` no matter how slow the
ladder is on this hardware. The measured duty is itself exported
(``ray_tpu_perf_sampler_duty_pct``): the overhead budget has a receipt.

Grading: each probe's best-seen step time is the baseline; the
regression ratio (latest/best) is exported and graded GREEN/YELLOW/RED
by ``TelemetryStore.perf_health`` with the same grade ladder the SLO
report uses.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.obs.perfwatch.sampler")

# a probe result: the StepProfile duck type (step, segments,
# measured_step_ms, coverage_pct, peak_tflops, meta)
ProbeFn = Callable[[], object]


def _profile_mfu_pct(profile) -> Optional[float]:
    """Model FLOPs utilization of the sampled step from the ladder's own
    cost model: attributed in-step FLOPs over measured wall at peak."""
    try:
        flops = sum(s.flops for s in profile.segments if s.in_step)
        sec = profile.measured_step_ms / 1e3
        peak = profile.peak_tflops * 1e12
        if flops <= 0 or sec <= 0 or peak <= 0:
            return None
        return 100.0 * flops / sec / peak
    except Exception:  # noqa: BLE001 - cost model absent on this profile
        return None


class PerfSampler:
    """Round-robins registered probes on a daemon thread, exporting each
    sample to the ``ray_tpu_perf_*`` telemetry series."""

    def __init__(self, interval_s: float = 60.0, max_duty: float = 0.01):
        if not 0.0 < max_duty <= 1.0:
            raise ValueError(f"max_duty must be in (0, 1], got {max_duty}")
        self.interval_s = float(interval_s)
        self.max_duty = float(max_duty)
        self._probes: "dict[str, ProbeFn]" = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # per-probe state for grading + the status surface
        self._best_ms: dict[str, float] = {}
        self._last: dict[str, dict] = {}
        self._errors: dict[str, str] = {}
        # trailing duty window: (probe wall, total wall) since start
        self._t_started = 0.0
        self._probe_wall_s = 0.0

    # -- probe registration ---------------------------------------------------

    def register(self, name: str, probe: ProbeFn) -> None:
        """Register a zero-arg probe returning a StepProfile. Probes run
        on the sampler thread — they must not touch live mutable state
        (the stock probes profile scratch copies)."""
        with self._lock:
            self._probes[name] = probe

    def attach_engine(self, engine, *, iters: int = 4, warmup: int = 1) -> None:
        """Sample decode-step segments of a live ``LLMEngine`` (scratch
        paged cache; live sequences untouched)."""
        self.register(
            "decode_step",
            lambda: engine.profile_decode(
                iters=iters, warmup=warmup, export_observability=False,
            ),
        )

    def attach_train_probe(self, config, params, batch, optimizer, *,
                           iters: int = 3, warmup: int = 1) -> None:
        """Sample train-step segments (incl. the split backward rungs and
        the all-reduce overlap probe) for a trainer's model state.

        ``params`` may be the pytree itself or a zero-arg callable
        returning the CURRENT pytree (a live trainer rebinds its state
        every step). The probe copies the leaves before profiling so a
        donating train step can't pull buffers out from under the
        ladder; a donation racing the copy fails one sample (logged,
        retried next round), never the trainer."""
        from ray_tpu.profiler import profile_train_step

        def probe():
            import jax
            import jax.numpy as jnp

            p = params() if callable(params) else params
            p = jax.tree.map(jnp.copy, p)
            return profile_train_step(
                config, p, batch, optimizer,
                iters=iters, warmup=warmup, export_observability=False,
            )

        self.register("train_step", probe)

    # -- sampling -------------------------------------------------------------

    def sample_once(self, name: str) -> Optional[dict]:
        """Run one registered probe now (synchronously), export its
        sample, and return the summary (None on probe failure). The
        bench harness calls this directly; the background loop goes
        through here too."""
        with self._lock:
            probe = self._probes.get(name)
        if probe is None:
            raise KeyError(f"no probe registered as {name!r}")
        t0 = time.perf_counter()
        try:
            profile = probe()
        except Exception as e:  # noqa: BLE001 - a broken probe must not kill the loop
            logger.warning("perf probe %s failed: %r", name, e)
            with self._lock:
                self._errors[name] = repr(e)[:200]
            return None
        wall_s = time.perf_counter() - t0
        summary = self._export(name, profile, wall_s)
        with self._lock:
            self._probe_wall_s += wall_s
            self._errors.pop(name, None)
            self._last[name] = summary
        return summary

    def _export(self, name: str, profile, wall_s: float) -> dict:
        from ray_tpu.obs.perfwatch import metrics as pm

        step = getattr(profile, "step", name)
        step_ms = float(profile.measured_step_ms)
        seg_hist = pm.perf_segment_histogram()
        overlap = None
        for seg in profile.segments:
            if seg.in_step:
                seg_hist.observe(seg.ms, tags={"step": step,
                                               "segment": seg.name})
        pm.perf_step_ms_gauge().set(step_ms, tags={"step": step})
        pm.perf_coverage_gauge().set(float(profile.coverage_pct),
                                     tags={"step": step})
        mfu = _profile_mfu_pct(profile)
        if mfu is not None:
            pm.perf_mfu_gauge().set(mfu, tags={"step": step})
        meta = getattr(profile, "meta", None) or {}
        if meta.get("allreduce_overlap_ratio") is not None:
            overlap = float(meta["allreduce_overlap_ratio"])
            pm.perf_overlap_gauge().set(overlap, tags={"step": step})
        with self._lock:
            best = min(self._best_ms.get(step, step_ms), step_ms)
            self._best_ms[step] = best
        ratio = step_ms / best if best > 0 else 1.0
        pm.perf_regression_gauge().set(ratio, tags={"step": step})
        pm.perf_samples_counter().inc(tags={"step": step})
        return {
            "step": step,
            "step_ms": round(step_ms, 4),
            "best_ms": round(best, 4),
            "regression_ratio": round(ratio, 4),
            "coverage_pct": float(profile.coverage_pct),
            "mfu_pct": round(mfu, 3) if mfu is not None else None,
            "overlap_ratio": overlap,
            "probe_wall_s": round(wall_s, 3),
        }

    # -- duty accounting ------------------------------------------------------

    def _duty_pct_locked(self) -> float:
        if not self._t_started:
            return 0.0
        total = time.monotonic() - self._t_started
        return 100.0 * self._probe_wall_s / total if total > 0 else 0.0

    def duty_pct(self) -> float:
        """Probe wall-clock share since start() (0 before the loop runs)."""
        with self._lock:
            return self._duty_pct_locked()

    def _next_sleep(self, last_probe_s: float) -> float:
        """At least interval_s; stretched so last_probe_s / (sleep +
        last_probe_s) ≤ max_duty — a slow ladder throttles itself."""
        budget_sleep = last_probe_s / self.max_duty - last_probe_s
        return max(self.interval_s, budget_sleep)

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        with self._lock:
            self._t_started = time.monotonic()
            self._probe_wall_s = 0.0
        self._thread = threading.Thread(
            target=self._loop, name="perfwatch-sampler", daemon=True
        )
        self._thread.start()

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
        self._thread = None

    def _loop(self) -> None:
        from ray_tpu.obs.perfwatch import metrics as pm

        idx = 0
        while not self._stop.is_set():
            with self._lock:
                names = sorted(self._probes)
            if not names:
                if self._stop.wait(timeout=min(self.interval_s, 1.0)):
                    return
                continue
            name = names[idx % len(names)]
            idx += 1
            t0 = time.perf_counter()
            try:
                self.sample_once(name)
            except Exception:  # noqa: BLE001 - never kill the loop
                logger.exception("perf sampler iteration failed")
            probe_s = time.perf_counter() - t0
            pm.perf_duty_gauge().set(self.duty_pct())
            if self._stop.wait(timeout=self._next_sleep(probe_s)):
                return

    # -- status ---------------------------------------------------------------

    def summary(self) -> dict:
        with self._lock:
            return {
                "probes": sorted(self._probes),
                "last": {k: dict(v) for k, v in self._last.items()},
                "errors": dict(self._errors),
                "duty_pct": round(self._duty_pct_locked(), 4)
                if self._t_started else None,
            }
