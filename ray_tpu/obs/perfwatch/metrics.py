"""Perf-sampler telemetry series (``ray_tpu_perf_*``).

The always-on sampler (sampler.py) periodically re-runs the chained-
probe ladders on live trainer steps and engine decode and exports what
it measures here, so a slow regression shows up on `ray_tpu status` and
the dashboard ``/api/perf`` route BETWEEN bench captures — not three
weeks later when someone re-runs bench.py.

Aggregation contract (scripts/check_metrics.py gate): step-level
gauges roll up MAX across reporters — a fleet's "step time" is its
worst profiled step, a summed step time is meaningless — and the
per-segment histogram bucket-merges.
"""

from __future__ import annotations

# same ladder as profiler/trace.py: micro-segments on CPU smoke models
# sit well under 1 ms; a wedged segment on a real device reaches 100s ms
_SEGMENT_MS_BOUNDARIES = [
    0.01, 0.05, 0.1, 0.5, 1, 2, 5, 10, 25, 50, 100, 250, 500, 1000,
]


def perf_segment_histogram():
    """Attributed wall time per sampled step segment, by (step,
    segment) — the distribution over samples, not one capture's point
    estimate."""
    from ray_tpu.obs.telemetry import cluster_histogram

    return cluster_histogram(
        "perf_segment_ms",
        description="perf sampler: attributed wall time per step "
        "segment across samples (ms)",
        boundaries=_SEGMENT_MS_BOUNDARIES,
        tag_keys=("step", "segment"),
    )


def perf_step_ms_gauge():
    from ray_tpu.obs.telemetry import AGG_MAX, cluster_gauge

    return cluster_gauge(
        "perf_step_ms",
        description="perf sampler: latest sampled whole-step wall time "
        "(ms), by step",
        tag_keys=("step",),
        agg=AGG_MAX,
    )


def perf_coverage_gauge():
    from ray_tpu.obs.telemetry import AGG_MAX, cluster_gauge

    return cluster_gauge(
        "perf_coverage_pct",
        description="perf sampler: % of the sampled step attributed to "
        "segments (probe honesty), by step",
        tag_keys=("step",),
        agg=AGG_MAX,
    )


def perf_mfu_gauge():
    from ray_tpu.obs.telemetry import AGG_MAX, cluster_gauge

    return cluster_gauge(
        "perf_mfu_pct",
        description="perf sampler: model FLOPs utilization of the "
        "sampled step (%), by step",
        tag_keys=("step",),
        agg=AGG_MAX,
    )


def perf_overlap_gauge():
    from ray_tpu.obs.telemetry import AGG_MAX, cluster_gauge

    return cluster_gauge(
        "perf_overlap_ratio",
        description="perf sampler: gradient all-reduce compute-overlap "
        "ratio (1.0 = fully hidden), by step",
        tag_keys=("step",),
        agg=AGG_MAX,
    )


def perf_regression_gauge():
    """current step_ms / best-seen step_ms, by step: 1.0 = at the best
    this process ever sampled; the perf_health grader reads this."""
    from ray_tpu.obs.telemetry import AGG_MAX, cluster_gauge

    return cluster_gauge(
        "perf_step_regression_ratio",
        description="perf sampler: latest sampled step time over the "
        "best-seen step time (1.0 = no regression), by step",
        tag_keys=("step",),
        agg=AGG_MAX,
    )


def perf_samples_counter():
    from ray_tpu.obs.telemetry import cluster_counter

    return cluster_counter(
        "perf_samples_total",
        description="perf sampler: profile samples taken, by step",
        tag_keys=("step",),
    )


def perf_duty_gauge():
    """Fraction of wall-clock the sampler actually spent probing (its
    overhead budget is max_duty; this gauge is the receipt)."""
    from ray_tpu.obs.telemetry import AGG_MAX, cluster_gauge

    return cluster_gauge(
        "perf_sampler_duty_pct",
        description="perf sampler: % of wall-clock spent inside probes "
        "over the trailing window (budgeted by max_duty)",
        agg=AGG_MAX,
    )


def register_metrics() -> None:
    """scripts/check_metrics.py hook: force lazy metrics to register."""
    perf_segment_histogram()
    perf_step_ms_gauge()
    perf_coverage_gauge()
    perf_mfu_gauge()
    perf_overlap_gauge()
    perf_regression_gauge()
    perf_samples_counter()
    perf_duty_gauge()
