"""ray_tpu.obs.telemetry — the cluster-wide metrics plane.

Every process-local ``util/metrics`` registry (node daemons, engine
hosts, the serve controller) periodically ships a snapshot to the GCS,
which keeps a bounded time-series ring per (reporter, metric, labels)
and serves cluster-level aggregation. Reference analog: the reference's
node metrics-agent -> GCS -> dashboard pipeline (SURVEY L0/L3), with the
opencensus hop collapsed into the snapshot wire form of
``util/metrics.snapshot_registry``.

Correctness contract (chaos-tested):

 * counters/histograms travel as MONOTONIC TOTALS per process epoch —
   a dropped or delayed ``telemetry_push`` only costs freshness; the
   next snapshot carries the full totals, so aggregates never double
   count and never go backwards;
 * a process restart bumps ``epoch``: the store banks the dead epoch's
   final totals into ``base`` and the new epoch counts from zero — no
   negative deltas;
 * re-ordered deliveries (a delayed RPC landing after a newer one) are
   dropped by ``seq``;
 * staleness per reporter is itself reported
   (``ray_tpu_telemetry_staleness_seconds``).

Aggregation semantics are DECLARED per metric (``sum`` / ``max`` /
``merge``) and travel with the snapshot, so the GCS needs no imports of
the instrumented modules. Histogram ``merge`` is bucket-wise vector
addition: percentiles of the merged vector equal percentiles over the
union of the per-replica observations to within one bucket width
(property-tested in tests/test_telemetry.py).

On top of the store: an SLO evaluator that grades each model tag
green/yellow/red from the MERGED TTFT/TPOT/queue-wait histograms — the
exact input the SLO-driven autoscaler (ROADMAP item 4) consumes — and
``format_status``, the renderer behind ``scripts/ray_tpu_status.py``.
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time
from collections import deque
from typing import Any, Callable, Optional

from ray_tpu.util import metrics as metrics_mod
from ray_tpu.util.metrics import Counter, Gauge, Histogram, _fq
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.obs.telemetry")

# -- aggregation kinds --------------------------------------------------------

AGG_SUM = "sum"      # cluster value = sum over reporters (capacity, totals)
AGG_MAX = "max"      # cluster value = max over reporters (worst-case view)
AGG_MERGE = "merge"  # histograms: bucket-wise vector addition
VALID_AGGREGATIONS = frozenset({AGG_SUM, AGG_MAX, AGG_MERGE})

# Name prefixes the telemetry plane aggregates: every gauge/counter under
# these MUST declare an aggregation kind (scripts/check_metrics.py gate).
AGGREGATED_PREFIXES = (
    "ray_tpu_node_",
    "ray_tpu_serve_",
    "ray_tpu_telemetry_",
    "ray_tpu_llm_",
    "ray_tpu_profiler_",
    "ray_tpu_train_",
    "ray_tpu_fabric_",
    # r19: RL post-training actor/learner plane (rl/post_train) — the
    # version-skew/trajectory-lag series behind `== rl post-train ==`
    "ray_tpu_rl_post_",
    # r20: SLO closed-loop pool autoscaler (autoscale) — decisions,
    # scale events, cold-start timings behind `== autoscaler ==`
    "ray_tpu_autoscale_",
    # r21: multi-tenant model fleet (fleet) — adapter residency churn,
    # canary outcomes, per-tenant routing volume behind `== fleet ==`
    "ray_tpu_fleet_",
    # r22: perfwatch sampled step profiling (obs.perfwatch) — segment
    # times, coverage, MFU, overlap, regression ratio behind `== perf ==`
    "ray_tpu_perf_",
)

_AGGREGATIONS: dict[str, str] = {}


def declare_aggregation(name: str, kind: str) -> None:
    """Declare how a metric aggregates across reporters. Names are
    fully-qualified the same way the registry qualifies them."""
    if kind not in VALID_AGGREGATIONS:
        raise ValueError(
            f"aggregation kind {kind!r} not in {sorted(VALID_AGGREGATIONS)}"
        )
    _AGGREGATIONS[_fq(name)] = kind


def aggregation_kind(name: str, metric_type: Optional[str] = None) -> Optional[str]:
    """Declared kind, else the per-type default: counters sum, histograms
    merge; gauges have NO default (sum-vs-max is a semantic choice the
    owner must make — that's the check_metrics lint)."""
    k = _AGGREGATIONS.get(_fq(name))
    if k is not None:
        return k
    if metric_type == "counter":
        return AGG_SUM
    if metric_type == "histogram":
        return AGG_MERGE
    return None


def cluster_counter(name: str, description: str = "",
                    tag_keys: Optional[tuple] = None,
                    agg: str = AGG_SUM) -> Counter:
    declare_aggregation(name, agg)
    return Counter(name, description=description, tag_keys=tag_keys)


def cluster_gauge(name: str, description: str = "",
                  tag_keys: Optional[tuple] = None,
                  agg: str = AGG_SUM) -> Gauge:
    declare_aggregation(name, agg)
    return Gauge(name, description=description, tag_keys=tag_keys)


def cluster_histogram(name: str, description: str = "",
                      boundaries: Optional[list] = None,
                      tag_keys: Optional[tuple] = None) -> Histogram:
    declare_aggregation(name, AGG_MERGE)
    return Histogram(name, description=description, boundaries=boundaries,
                     tag_keys=tag_keys)


# -- histogram math (pure, property-tested) -----------------------------------


def merge_bucket_vectors(vectors: list) -> list:
    """Bucket-wise sum of same-shape histogram vectors."""
    if not vectors:
        return []
    n = len(vectors[0])
    out = [0] * n
    for v in vectors:
        if len(v) != n:
            raise ValueError(
                f"cannot merge bucket vectors of length {len(v)} and {n} "
                "(boundary mismatch)"
            )
        for i, x in enumerate(v):
            out[i] += x
    return out


def bucket_percentile(boundaries: list, buckets: list, q: float) -> Optional[float]:
    """Nearest-rank percentile estimate from a bucket vector: the UPPER
    boundary of the bucket holding the rank-q observation (the +Inf
    bucket reports the last finite boundary — the best known lower
    bound). By construction the true union-of-observations nearest-rank
    percentile lies inside the same bucket, i.e. the estimate is exact to
    within one bucket width."""
    total = sum(buckets)
    if total <= 0:
        return None
    rank = max(1, math.ceil(q / 100.0 * total))
    cum = 0
    for i, n in enumerate(buckets):
        cum += n
        if cum >= rank:
            return float(boundaries[i]) if i < len(boundaries) else float(boundaries[-1])
    return float(boundaries[-1])


def bucket_percentile_band(boundaries: list, buckets: list,
                           q: float) -> Optional[tuple]:
    """(lower, upper) bounds of the bucket holding the rank-q observation
    (upper = +inf for the overflow bucket) — the containment interval the
    merge-correctness property test asserts against."""
    total = sum(buckets)
    if total <= 0:
        return None
    rank = max(1, math.ceil(q / 100.0 * total))
    cum = 0
    for i, n in enumerate(buckets):
        cum += n
        if cum >= rank:
            lo = float(boundaries[i - 1]) if i > 0 else float("-inf")
            hi = float(boundaries[i]) if i < len(boundaries) else float("inf")
            return (lo, hi)
    return (float(boundaries[-1]), float("inf"))


# -- SLO evaluation -----------------------------------------------------------

GRADE_GREEN = "green"
GRADE_YELLOW = "yellow"
GRADE_RED = "red"
GRADE_NO_DATA = "no_data"
_GRADE_ORDER = {GRADE_NO_DATA: 0, GRADE_GREEN: 1, GRADE_YELLOW: 2, GRADE_RED: 3}

# the three merged histograms the evaluator grades, by registry name
SLO_HISTOGRAMS = {
    "ttft": _fq("llm_ttft_seconds"),
    "tpot": _fq("llm_tpot_seconds"),
    "queue_wait": _fq("llm_queue_wait_seconds"),
}


@dataclasses.dataclass
class SLOThresholds:
    """Green thresholds at ``percentile``; yellow up to
    ``yellow_factor`` x threshold, red beyond. Defaults sized for a CPU
    smoke model — production configs come from the serving deployment."""

    ttft_p_s: float = 2.0
    tpot_p_s: float = 0.2
    queue_wait_p_s: float = 1.0
    percentile: float = 95.0
    yellow_factor: float = 2.0
    min_count: int = 1

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "SLOThresholds":
        if not d:
            return cls()
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})


def grade_value(value: Optional[float], threshold: float,
                yellow_factor: float) -> str:
    if value is None:
        return GRADE_NO_DATA
    if value <= threshold:
        return GRADE_GREEN
    if value <= threshold * yellow_factor:
        return GRADE_YELLOW
    return GRADE_RED


def evaluate_slo(histograms: dict, thresholds: Optional[SLOThresholds] = None) -> dict:
    """Grade every model tag from MERGED SLO histograms.

    ``histograms``: {registry_name: {model_tag: {"boundaries", "buckets",
    "sum", "count"}}} — the shape ``TelemetryStore.cluster_metrics``
    produces. Output is the autoscaler's input: per-tag grades with the
    signal->pool mapping made explicit (TTFT prices the prefill pool,
    TPOT the decode pool, queue_wait admission/overall capacity)."""
    th = thresholds or SLOThresholds()
    limits = {
        "ttft": th.ttft_p_s,
        "tpot": th.tpot_p_s,
        "queue_wait": th.queue_wait_p_s,
    }
    tags: set = set()
    for name in SLO_HISTOGRAMS.values():
        tags.update((histograms.get(name) or {}).keys())
    out: dict = {"thresholds": th.to_dict(), "model_tags": {}}
    for tag in sorted(tags):
        entry: dict = {}
        worst = GRADE_NO_DATA
        for short, name in SLO_HISTOGRAMS.items():
            h = (histograms.get(name) or {}).get(tag)
            count = int(h["count"]) if h else 0
            p = None
            if h and count >= th.min_count:
                p = bucket_percentile(h["boundaries"], h["buckets"], th.percentile)
            g = grade_value(p, limits[short], th.yellow_factor)
            entry[short] = {
                "count": count,
                f"p{th.percentile:g}": p,
                "p50": bucket_percentile(h["boundaries"], h["buckets"], 50.0)
                if h else None,
                "threshold_s": limits[short],
                "grade": g,
            }
            if _GRADE_ORDER[g] > _GRADE_ORDER[worst]:
                worst = g
        entry["grade"] = worst
        # the closed-loop mapping ROADMAP item 4 consumes: which pool a
        # breached signal points at
        entry["autoscaler_hints"] = {
            "scale_prefill": entry["ttft"]["grade"] in (GRADE_YELLOW, GRADE_RED),
            "scale_decode": entry["tpot"]["grade"] in (GRADE_YELLOW, GRADE_RED),
            "shed_or_add_capacity":
                entry["queue_wait"]["grade"] in (GRADE_YELLOW, GRADE_RED),
        }
        out["model_tags"][tag] = entry
    return out


# -- reporter-side ------------------------------------------------------------


def pushes_counter() -> Counter:
    return cluster_counter(
        "telemetry_pushes_total",
        description="telemetry snapshots this process attempted to ship "
        "to the GCS, by result (ok / dropped / error)",
        tag_keys=("result",),
        agg=AGG_SUM,
    )


def staleness_gauge() -> Gauge:
    return cluster_gauge(
        "telemetry_staleness_seconds",
        description="seconds since each reporter's last accepted "
        "telemetry push (set GCS-side at aggregation time; a partitioned "
        "or crashed reporter shows up here, never as silent absence)",
        tag_keys=("reporter",),
        agg=AGG_MAX,
    )


def register_metrics() -> None:
    """scripts/check_metrics.py hook: force telemetry-plane metrics to
    register and their aggregation kinds to be declared."""
    pushes_counter()
    staleness_gauge()


def annotated_snapshot(
    series_filter: Optional[Callable[[str, dict], bool]] = None,
) -> dict:
    """util/metrics.snapshot_registry + per-metric aggregation kinds, so
    declarations travel with the data and the GCS never imports the
    instrumented modules."""
    snap = metrics_mod.snapshot_registry(series_filter)
    for entry in snap["metrics"]:
        agg = aggregation_kind(entry["name"], entry["type"])
        if agg is not None:
            entry["agg"] = agg
    return snap


class TelemetryReporter:
    """Ships this process's metrics registry to the GCS on an interval.

    ``collect`` callbacks run right before each snapshot (refresh
    utilization gauges from live state); failures in them — and in the
    push itself — never propagate: telemetry loss is staleness, by
    design. Chaos's DROP_RPC/DELAY_RPC specs match the push at the
    ``rpc.call`` site with ``method="telemetry_push"``."""

    def __init__(
        self,
        gcs_addr: Optional[tuple] = None,
        *,
        reporter_id: str,
        kind: str = "process",
        role: str = "",
        interval_s: float = 2.0,
        series_filter: Optional[Callable[[str, dict], bool]] = None,
        collect: Optional[list] = None,
        client: Any = None,
        timeout_s: float = 5.0,
    ):
        if client is None and gcs_addr is None:
            raise ValueError("need gcs_addr or an rpc client")
        self.reporter_id = reporter_id
        self.kind = kind
        self.role = role
        self.interval_s = float(interval_s)
        self._series_filter = series_filter
        self._collect = list(collect or ())
        self._timeout = timeout_s
        self._client = client
        self._gcs_addr = tuple(gcs_addr) if gcs_addr else None
        self._owns_client = client is None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.num_ok = 0
        self.num_dropped = 0

    def _get_client(self):
        if self._client is None:
            from ray_tpu.cluster.rpc import ReconnectingRpcClient

            self._client = ReconnectingRpcClient(
                *self._gcs_addr, timeout=self._timeout
            ).connect(retries=5)
        return self._client

    def add_collect(self, fn: Callable[[], None]) -> None:
        self._collect.append(fn)

    def snapshot(self) -> dict:
        for fn in self._collect:
            try:
                fn()
            except Exception:  # noqa: BLE001 — telemetry must not break serving
                logger.exception("telemetry collect callback failed")
        return annotated_snapshot(self._series_filter)

    def push_once(self) -> bool:
        """One snapshot->push round. False = this push was lost (the next
        one re-carries the full totals; nothing to retry)."""
        from ray_tpu.cluster.rpc import RemoteError, RpcError

        snap = self.snapshot()
        try:
            self._get_client().call(
                "telemetry_push",
                {
                    "reporter_id": self.reporter_id,
                    "kind": self.kind,
                    "role": self.role,
                    "snapshot": snap,
                },
                timeout=self._timeout,
            )
        except (RpcError, RemoteError):
            self.num_dropped += 1
            try:
                pushes_counter().inc(tags={"result": "dropped"})
            except Exception:  # noqa: BLE001
                pass
            return False
        self.num_ok += 1
        try:
            pushes_counter().inc(tags={"result": "ok"})
        except Exception:  # noqa: BLE001
            pass
        return True

    def start(self) -> "TelemetryReporter":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._loop, name=f"telemetry-{self.reporter_id}", daemon=True
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.push_once()
            except Exception:  # noqa: BLE001 — the loop must never die
                logger.exception("telemetry push failed")

    def stop(self, final_push: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        if final_push:
            try:
                self.push_once()
            except Exception:  # noqa: BLE001
                pass
        if self._owns_client and self._client is not None:
            try:
                self._client.close()
            except Exception:  # noqa: BLE001
                pass
            self._client = None


# -- GCS-side store -----------------------------------------------------------


class TelemetryStore:
    """Bounded time-series store + cluster aggregation (lives inside the
    GCS service; one instance per control plane).

    Per (reporter, metric, labels) series state: ``base`` (totals banked
    from dead process epochs), ``last`` (the live epoch's running total
    or gauge value), and a ring of (wall_ts, cumulative) points bounded
    by ``ring_len`` — enough history for rate computation (bytes/s) and
    a recent-window sparkline without unbounded growth."""

    def __init__(self, ring_len: int = 240, rate_window_s: float = 60.0,
                 expire_after_s: float = 900.0):
        self._lock = threading.RLock()
        self.ring_len = int(ring_len)
        self.rate_window_s = float(rate_window_s)
        # reporters silent this long are evicted with all their series:
        # partitioned nodes show up as STALE well before this (staleness
        # is the signal), but a decommissioned/renamed reporter must not
        # contribute its last gauge values to sum rollups forever
        self.expire_after_s = float(expire_after_s)
        self._reporters: dict[str, dict] = {}
        self._series: dict[tuple, dict] = {}
        self._meta: dict[str, dict] = {}
        self.num_ingested = 0
        self.num_ignored_stale = 0
        self.num_expired = 0

    # -- writes ---------------------------------------------------------------

    def ingest(self, reporter_id: str, snapshot: dict,
               meta: Optional[dict] = None) -> dict:
        now_m, now_w = time.monotonic(), time.time()
        with self._lock:
            out = self._ingest_one_locked(reporter_id, snapshot, meta,
                                          now_m, now_w)
            self._reap(now_m)
        return out

    def ingest_batch(self, items: list) -> list:
        """Coalesced ingest (r20 control-plane batching): N snapshots —
        ``(reporter_id, snapshot, meta)`` tuples — under ONE lock
        acquisition and ONE reap sweep, for the GCS's batched heartbeat/
        telemetry frames. Per-item epoch/seq guards are identical to
        ``ingest``; results are returned in order."""
        now_m, now_w = time.monotonic(), time.time()
        out: list = []
        with self._lock:
            for reporter_id, snapshot, meta in items:
                out.append(
                    self._ingest_one_locked(reporter_id, snapshot, meta,
                                            now_m, now_w)
                )
            self._reap(now_m)
        return out

    def _ingest_one_locked(self, reporter_id: str, snapshot: dict,
                           meta: Optional[dict], now_m: float,
                           now_w: float) -> dict:
        """One snapshot's epoch/seq-guarded ingest; caller holds
        ``self._lock`` (and runs ``_reap`` once per lock acquisition)."""
        epoch = str(snapshot.get("epoch", ""))
        seq = int(snapshot.get("seq", 0))
        rep = self._reporters.get(reporter_id)
        if rep is not None:
            if rep["epoch"] == epoch and seq <= rep["seq"]:
                # a delayed/duplicated push landing after a newer one:
                # ignoring it is what "monotonic re-send, never
                # double-count" means on the receive side
                self.num_ignored_stale += 1
                return {"ok": True, "ignored": "stale_seq"}
            if epoch in rep["dead_epochs"]:
                # a delayed pre-restart push landing after the new
                # epoch already reported: accepting it would re-bank
                # the live epoch's totals under the dead epoch's —
                # a PERMANENT double count. Its tail delta is lost,
                # which is staleness at the restart boundary, not
                # corruption.
                self.num_ignored_stale += 1
                return {"ok": True, "ignored": "stale_epoch"}
        if rep is None:
            rep = self._reporters[reporter_id] = {
                "kind": "", "role": "", "pushes": 0,
                "dead_epochs": deque(maxlen=16),
            }
        if rep.get("epoch") not in (None, epoch):
            rep["dead_epochs"].append(rep["epoch"])
        rep["epoch"] = epoch
        rep["seq"] = seq
        rep["last_push_monotonic"] = now_m
        rep["last_push_wall"] = now_w
        rep["reporter_ts_wall"] = float(snapshot.get("ts_wall", now_w))
        rep["pushes"] += 1
        m = meta or {}
        if m.get("kind"):
            rep["kind"] = m["kind"]
        if m.get("role"):
            rep["role"] = m["role"]
        for entry in snapshot.get("metrics", ()):
            self._ingest_metric(reporter_id, epoch, now_w, entry)
        self.num_ingested += 1
        return {"ok": True}

    def _reap(self, now_m: float) -> None:
        """Evict reporters (and all their series) silent past
        ``expire_after_s`` — must hold the lock. Counter totals they
        contributed leave the aggregate with them: a reporter gone that
        long is decommissioned, and keeping its last gauges would count
        phantoms in every sum rollup while `_series` grows without bound
        under reporter churn."""
        dead = [
            rid for rid, rep in self._reporters.items()
            if now_m - rep["last_push_monotonic"] > self.expire_after_s
        ]
        for rid in dead:
            del self._reporters[rid]
            for key in [k for k in self._series if k[0] == rid]:
                del self._series[key]
            self.num_expired += 1
            try:
                staleness_gauge().remove_series(tags={"reporter": rid})
            except Exception:  # noqa: BLE001
                pass

    def _ingest_metric(self, reporter_id: str, epoch: str, now_w: float,
                       entry: dict) -> None:
        name = entry["name"]
        mtype = entry["type"]
        meta = self._meta.setdefault(name, {})
        meta["type"] = mtype
        if entry.get("description"):
            meta["description"] = entry["description"]
        meta["tag_keys"] = list(entry.get("tag_keys", ()))
        if "boundaries" in entry:
            meta["boundaries"] = list(entry["boundaries"])
        if entry.get("agg"):
            meta["agg"] = entry["agg"]
        for s in entry.get("series", ()):
            key = (reporter_id, name, tuple(s.get("tags", ())))
            st = self._series.get(key)
            if mtype == "histogram":
                buckets = [int(b) for b in s["buckets"]]
                zero = [0] * len(buckets)
                if st is None or len(st["last"]) != len(buckets):
                    # new series, or boundaries changed across a restart
                    # (vector shapes no longer merge): start clean
                    st = self._series[key] = {
                        "epoch": epoch, "base": list(zero), "last": list(zero),
                        "base_sum": 0.0, "last_sum": 0.0,
                        "base_count": 0, "last_count": 0,
                        "ring": deque(maxlen=self.ring_len),
                    }
                if st["epoch"] != epoch:
                    # restart: bank the dead epoch's final totals
                    st["base"] = [a + b for a, b in zip(st["base"], st["last"])]
                    st["base_sum"] += st["last_sum"]
                    st["base_count"] += st["last_count"]
                    st["epoch"] = epoch
                st["last"] = buckets
                st["last_sum"] = float(s.get("sum", 0.0))
                st["last_count"] = int(s.get("count", 0))
                st["ring"].append((now_w, st["base_count"] + st["last_count"]))
            elif mtype == "counter":
                val = float(s["value"])
                if st is None:
                    st = self._series[key] = {
                        "epoch": epoch, "base": 0.0, "last": 0.0,
                        "ring": deque(maxlen=self.ring_len),
                    }
                if st["epoch"] != epoch:
                    st["base"] += st["last"]
                    st["epoch"] = epoch
                    st["last"] = 0.0
                # max(): counters are monotonic within an epoch; a lower
                # value here could only be clock-free reordering the seq
                # guard already rejects — belt and braces
                st["last"] = max(st["last"], val)
                st["ring"].append((now_w, st["base"] + st["last"]))
            else:  # gauge: last write (per reporter) wins
                val = float(s["value"])
                if st is None:
                    st = self._series[key] = {
                        "epoch": epoch, "last": val,
                        "ring": deque(maxlen=self.ring_len),
                    }
                st["epoch"] = epoch
                st["last"] = val
                st["ring"].append((now_w, val))

    # -- reads ----------------------------------------------------------------

    @staticmethod
    def _tags_key(tag_keys: list, tags: tuple) -> str:
        """Stable string key for one tag combination. Values are escaped
        (``\\`` then ``,`` and ``=``) so a tag value containing the
        separators survives the round trip through `_parse_tags_key` —
        unescaped, `model=llama,8b` would re-parse as {model: llama} and
        be graded/grouped as the wrong tag."""
        if not tag_keys:
            return ""
        esc = (
            lambda v: str(v)
            .replace("\\", "\\\\")
            .replace(",", "\\,")
            .replace("=", "\\=")
        )
        return ",".join(f"{k}={esc(v)}" for k, v in zip(tag_keys, tags))

    @staticmethod
    def _parse_tags_key(skey: str) -> dict:
        """Inverse of `_tags_key` (tag KEYS are identifiers; only values
        carry escapes)."""
        if not skey:
            return {}
        out: dict = {}
        k: Optional[str] = None
        buf: list[str] = []
        it = iter(skey)
        for ch in it:
            if ch == "\\":
                buf.append(next(it, ""))
            elif ch == "=" and k is None:
                k = "".join(buf)
                buf = []
            elif ch == ",":
                if k is not None:
                    out[k] = "".join(buf)
                k, buf = None, []
            else:
                buf.append(ch)
        if k is not None:
            out[k] = "".join(buf)
        return out

    def _rate(self, ring: deque, now_w: float) -> float:
        """Per-second rate over the recent window from cumulative points."""
        if len(ring) < 2:
            return 0.0
        cutoff = now_w - self.rate_window_s
        pts = list(ring)
        first = pts[0]
        for p in pts:
            if p[0] >= cutoff:
                first = p
                break
        last = pts[-1]
        dt = last[0] - first[0]
        if dt <= 0:
            return 0.0
        return max(0.0, (last[1] - first[1]) / dt)

    def staleness(self) -> dict:
        """Seconds since each reporter's last accepted push (monotonic
        clock — wall-clock skew between hosts can't fake freshness).
        Also mirrored into this process's own registry so the merged
        exposition and /metrics carry it."""
        now_m = time.monotonic()
        with self._lock:
            self._reap(now_m)
            out = {
                rid: round(now_m - rep["last_push_monotonic"], 3)
                for rid, rep in self._reporters.items()
            }
        try:
            g = staleness_gauge()
            for rid, s in out.items():
                g.set(s, tags={"reporter": rid})
        except Exception:  # noqa: BLE001
            pass
        return out

    def cluster_metrics(self) -> dict:
        """The cluster-level aggregate: counter sums (+ windowed rates),
        gauge sum/max rollups, bucket-wise histogram merges with
        percentile estimates, per-reporter staleness."""
        now_w = time.time()
        staleness = self.staleness()
        with self._lock:
            reporters = {
                rid: {
                    "kind": rep.get("kind", ""),
                    "role": rep.get("role", ""),
                    "epoch": rep.get("epoch", ""),
                    "seq": rep.get("seq", 0),
                    "pushes": rep.get("pushes", 0),
                    "last_push_wall": rep.get("last_push_wall", 0.0),
                    "staleness_s": staleness.get(rid),
                }
                for rid, rep in self._reporters.items()
            }
            counters: dict = {}
            gauges: dict = {}
            hists: dict = {}
            for (rid, name, tags), st in self._series.items():
                meta = self._meta.get(name, {})
                mtype = meta.get("type", "gauge")
                skey = self._tags_key(meta.get("tag_keys", ()), tags)
                if mtype == "counter":
                    acc = counters.setdefault(name, {
                        "agg": meta.get("agg", AGG_SUM),
                        "description": meta.get("description", ""),
                        "total": 0.0, "series": {}, "rate_per_s": {},
                    })
                    cum = st["base"] + st["last"]
                    acc["total"] += cum
                    acc["series"][skey] = acc["series"].get(skey, 0.0) + cum
                    acc["rate_per_s"][skey] = round(
                        acc["rate_per_s"].get(skey, 0.0)
                        + self._rate(st["ring"], now_w), 6,
                    )
                elif mtype == "histogram":
                    acc = hists.setdefault(name, {
                        "agg": meta.get("agg", AGG_MERGE),
                        "description": meta.get("description", ""),
                        "boundaries": meta.get("boundaries", []),
                        "series": {},
                    })
                    merged = acc["series"].get(skey)
                    cum_buckets = [
                        a + b for a, b in zip(st["base"], st["last"])
                    ]
                    if merged is None:
                        merged = acc["series"][skey] = {
                            "buckets": list(cum_buckets),
                            "sum": 0.0, "count": 0,
                            "boundaries": acc["boundaries"],
                        }
                    else:
                        try:
                            merged["buckets"] = merge_bucket_vectors(
                                [merged["buckets"], cum_buckets]
                            )
                        except ValueError:
                            continue  # boundary drift: skip, don't corrupt
                    merged["sum"] += st["base_sum"] + st["last_sum"]
                    merged["count"] += st["base_count"] + st["last_count"]
                else:
                    kind = meta.get("agg") or AGG_SUM
                    acc = gauges.setdefault(name, {
                        "agg": kind,
                        "description": meta.get("description", ""),
                        "value": None, "series": {},
                    })
                    v = st["last"]
                    cur = acc["series"].get(skey)
                    if cur is None:
                        acc["series"][skey] = v
                    elif kind == AGG_MAX:
                        acc["series"][skey] = max(cur, v)
                    else:
                        acc["series"][skey] = cur + v
            for acc in gauges.values():
                vals = list(acc["series"].values())
                if vals:
                    acc["value"] = (
                        max(vals) if acc["agg"] == AGG_MAX else sum(vals)
                    )
            for acc in hists.values():
                for merged in acc["series"].values():
                    for q in (50.0, 90.0, 95.0, 99.0):
                        merged[f"p{q:g}"] = bucket_percentile(
                            merged["boundaries"], merged["buckets"], q
                        )
            return {
                "ts_wall": now_w,
                "reporters": reporters,
                "staleness": staleness,
                "counters": counters,
                "gauges": gauges,
                "histograms": hists,
                "ingested": self.num_ingested,
                "ignored_stale": self.num_ignored_stale,
            }

    def slo_histograms(self, agg: Optional[dict] = None) -> dict:
        """{registry_name: {model_tag: merged-series}} for the SLO
        evaluator, keyed off the histograms' ``model`` tag."""
        if agg is None:
            agg = self.cluster_metrics()
        out: dict = {}
        for short, name in SLO_HISTOGRAMS.items():
            acc = agg["histograms"].get(name)
            if not acc:
                continue
            per_tag: dict = {}
            for skey, merged in acc["series"].items():
                tag = self._parse_tags_key(skey).get("model", "")
                per_tag[tag] = merged
            out[name] = per_tag
        return out

    def slo_report(self, thresholds: Optional[SLOThresholds] = None,
                   agg: Optional[dict] = None) -> dict:
        if agg is None:
            agg = self.cluster_metrics()
        report = evaluate_slo(self.slo_histograms(agg), thresholds)
        report["staleness"] = agg["staleness"]
        return report

    def pool_rollups(self, agg: Optional[dict] = None) -> dict:
        """Role-keyed pool view from the serve controller's role-tagged
        replica gauges (r10 DeploymentConfig.role)."""
        if agg is None:
            agg = self.cluster_metrics()
        pools: dict = {}
        for name, field in (
            (_fq("serve_replicas_running"), "replicas_running"),
            (_fq("serve_replicas_target"), "replicas_target"),
        ):
            acc = agg["gauges"].get(name)
            if not acc:
                continue
            for skey, v in acc["series"].items():
                tags = self._parse_tags_key(skey)
                role = tags.get("role", "") or "(none)"
                pool = pools.setdefault(role, {
                    "replicas_running": 0, "replicas_target": 0,
                    "deployments": [],
                })
                pool[field] = pool.get(field, 0) + int(v)
                dep = f"{tags.get('app', '')}/{tags.get('deployment', '')}"
                if dep != "/" and dep not in pool["deployments"]:
                    pool["deployments"].append(dep)
        return pools

    def utilization(self, agg: Optional[dict] = None) -> dict:
        """The fleet utilization summary `ray_tpu status` prints."""
        if agg is None:
            agg = self.cluster_metrics()

        def gauge_total(name):
            acc = agg["gauges"].get(_fq(name))
            return acc["value"] if acc else None

        def counter_rate(name):
            acc = agg["counters"].get(_fq(name))
            if not acc:
                return None
            return round(sum(acc["rate_per_s"].values()), 3)

        out = {
            "kv_pages_used": gauge_total("llm_kv_pages_used"),
            "kv_pages_total": gauge_total("llm_kv_pages_total"),
            "kv_hbm_bytes": gauge_total("llm_kv_hbm_bytes"),
            "queue_depth": gauge_total("llm_queue_depth"),
            "running_requests": gauge_total("llm_running_requests"),
            "kv_transfer_bytes_per_s": counter_rate("llm_kv_transfer_bytes_total"),
            "spec_acceptance_rate": gauge_total("llm_spec_acceptance_rate"),
        }
        used, total = out["kv_pages_used"], out["kv_pages_total"]
        out["kv_page_occupancy"] = (
            round(used / total, 4) if used is not None and total else None
        )
        return out

    def prometheus_text(self) -> str:
        """Merged cluster-level Prometheus exposition (the fleet analog of
        each process's /metrics): one series per (metric, labels), summed/
        maxed/merged across reporters, plus the staleness gauge."""
        from ray_tpu.util.metrics import _escape_label_value

        agg = self.cluster_metrics()
        lines: list[str] = []

        def fmt_key(skey: str, extra: str = "") -> str:
            parts = [
                f'{k}="{_escape_label_value(v)}"'
                for k, v in self._parse_tags_key(skey).items()
            ]
            if extra:
                parts.append(extra)
            return "{" + ",".join(parts) + "}" if parts else ""

        for name in sorted(agg["counters"]):
            acc = agg["counters"][name]
            lines.append(f"# HELP {name} {acc['description']}")
            lines.append(f"# TYPE {name} counter")
            for skey, v in sorted(acc["series"].items()):
                lines.append(f"{name}{fmt_key(skey)} {v}")
        for name in sorted(agg["gauges"]):
            acc = agg["gauges"][name]
            lines.append(f"# HELP {name} {acc['description']}")
            lines.append(f"# TYPE {name} gauge")
            for skey, v in sorted(acc["series"].items()):
                lines.append(f"{name}{fmt_key(skey)} {v}")
        for name in sorted(agg["histograms"]):
            acc = agg["histograms"][name]
            lines.append(f"# HELP {name} {acc['description']}")
            lines.append(f"# TYPE {name} histogram")
            for skey, merged in sorted(acc["series"].items()):
                cum = 0
                for b, n in zip(merged["boundaries"], merged["buckets"]):
                    cum += n
                    le = 'le="%s"' % b
                    lines.append(f"{name}_bucket{fmt_key(skey, le)} {cum}")
                if len(merged["buckets"]) > len(merged["boundaries"]):
                    cum += merged["buckets"][-1]
                le_inf = 'le="+Inf"'
                lines.append(f"{name}_bucket{fmt_key(skey, le_inf)} {cum}")
                lines.append(f"{name}_sum{fmt_key(skey)} {merged['sum']}")
                lines.append(f"{name}_count{fmt_key(skey)} {merged['count']}")
        stale = agg["staleness"]
        sname = _fq("telemetry_staleness_seconds")
        lines.append(
            f"# HELP {sname} seconds since each reporter's last accepted "
            "telemetry push"
        )
        lines.append(f"# TYPE {sname} gauge")
        for rid, s in sorted(stale.items()):
            lines.append(
                f'{sname}{{reporter="{_escape_label_value(rid)}"}} {s}'
            )
        return "\n".join(lines) + "\n"

    def trainer_health(self, agg: Optional[dict] = None) -> dict:
        """Elastic-trainer rollup for `ray_tpu status` (r12): the
        fleet's current gang epoch (max over reporters — every recovery
        bumps it), completed recoveries and ranks lost (sums). All None
        when no trainer is reporting."""
        if agg is None:
            agg = self.cluster_metrics()

        def gauge(name):
            acc = agg["gauges"].get(_fq(name))
            return acc["value"] if acc else None

        def counter(name):
            acc = agg["counters"].get(_fq(name))
            return acc["total"] if acc else None

        return {
            "gang_epoch": gauge("train_gang_epoch"),
            "recoveries_total": counter("train_recoveries_total"),
            "ranks_lost_total": counter("train_ranks_lost_total"),
        }

    def fabric_health(self, agg: Optional[dict] = None) -> dict:
        """Transfer-fabric rollup for `ray_tpu status` (r15): active
        edges per transport backend (summed over orchestrators),
        device→rpc fallbacks burned, and the KV-byte mix per backend
        (from the backend-labelled transfer counter). All None/empty
        when no fabric is reporting."""
        if agg is None:
            agg = self.cluster_metrics()
        edges: dict[str, int] = {}
        acc = agg["gauges"].get(_fq("fabric_edges_active"))
        if acc:
            for skey, v in acc["series"].items():
                backend = self._parse_tags_key(skey).get("backend", "")
                edges[backend] = edges.get(backend, 0) + int(v)
        fallbacks = None
        acc = agg["counters"].get(_fq("fabric_transfer_fallbacks_total"))
        if acc:
            fallbacks = int(acc["total"])
        bytes_by_backend: dict[str, float] = {}
        acc = agg["counters"].get(_fq("llm_kv_transfer_bytes_total"))
        if acc:
            for skey, v in acc["series"].items():
                backend = self._parse_tags_key(skey).get("backend", "")
                bytes_by_backend[backend] = (
                    bytes_by_backend.get(backend, 0.0) + float(v)
                )
        return {
            "edges_by_backend": edges,
            "fallbacks_total": fallbacks,
            "kv_bytes_by_backend": bytes_by_backend,
        }

    def kvtier_health(self, agg: Optional[dict] = None) -> dict:
        """Tiered-KV-cache rollup for `ray_tpu status` (r17): resident
        spilled bytes per deep tier (gauge sum over engines), cumulative
        spilled bytes per destination tier, prefix-cache hit tokens per
        serving tier (the tier-labelled hit counter), resurrected
        tokens, and corrupt drops. All empty when no tiered cache is
        reporting."""
        if agg is None:
            agg = self.cluster_metrics()

        def by_tier(table: str, name: str) -> dict:
            out: dict[str, float] = {}
            acc = agg[table].get(_fq(name))
            if acc:
                for skey, v in acc["series"].items():
                    tier = self._parse_tags_key(skey).get("tier", "")
                    out[tier] = out.get(tier, 0.0) + float(v)
            return out

        corrupt = agg["counters"].get(_fq("llm_kvtier_corrupt_dropped_total"))
        # r18 (llm/kvfetch): prefetch phase totals, cross-engine fetch
        # bytes per backend, and the async-spill backlog gauge
        prefetch = {}
        for phase in ("started", "completed", "wasted"):
            acc = agg["counters"].get(
                _fq(f"llm_kvtier_prefetch_{phase}_total"))
            prefetch[phase] = int(acc["total"]) if acc else 0
        fetch_by_backend: dict[str, float] = {}
        acc = agg["counters"].get(_fq("llm_kvtier_fetch_bytes_total"))
        if acc:
            for skey, v in acc["series"].items():
                backend = self._parse_tags_key(skey).get("backend", "")
                fetch_by_backend[backend] = (
                    fetch_by_backend.get(backend, 0.0) + float(v)
                )
        spillq = agg["gauges"].get(_fq("llm_kvtier_spill_queue_depth"))
        return {
            "resident_bytes_by_tier": by_tier(
                "gauges", "llm_kvtier_resident_bytes"),
            "spilled_bytes_by_tier": by_tier(
                "counters", "llm_kvtier_spilled_bytes_total"),
            "hit_tokens_by_tier": by_tier(
                "counters", "llm_prefix_cache_hit_tokens_total"),
            "resurrected_tokens_by_tier": by_tier(
                "counters", "llm_kvtier_resurrected_tokens_total"),
            "corrupt_dropped_total": (
                int(corrupt["total"]) if corrupt else None
            ),
            "prefetch": prefetch,
            "fetch_bytes_by_backend": fetch_by_backend,
            "spill_queue_depth": (
                int(spillq["value"])
                if spillq and spillq.get("value") is not None else None
            ),
        }

    def rl_post_health(self, agg: Optional[dict] = None) -> dict:
        """RL post-training rollup for `ray_tpu status` (r19): weight
        version per tier (MAX over reporters — learner = last published,
        rollout = applied by serving engines; the difference IS the
        actor/learner skew), trajectory lag (queued between the tiers),
        overflow/staleness drops, publishes, rollout preemptions ridden
        out, and the worst staleness ever trained on (the audit surface
        for the max_staleness contract). All None/empty when no
        post-training loop is reporting."""
        if agg is None:
            agg = self.cluster_metrics()
        versions: dict[str, float] = {}
        acc = agg["gauges"].get(_fq("ray_tpu_rl_post_weight_version"))
        if acc:
            for skey, v in acc["series"].items():
                tier = self._parse_tags_key(skey).get("tier", "")
                # learner: the newest successful publish (max). rollout:
                # the WORST engine (min over per-actor series) — the
                # skew line must surface a laggard serving stale
                # weights, not let a healthy peer mask it
                if tier == "rollout" and tier in versions:
                    versions[tier] = min(versions[tier], float(v))
                else:
                    versions[tier] = max(versions.get(tier, 0.0), float(v))

        def counter(name):
            c = agg["counters"].get(_fq(name))
            return int(c["total"]) if c else None

        def gauge(name):
            g = agg["gauges"].get(_fq(name))
            return g["value"] if g else None

        return {
            "version_by_tier": versions,
            "queue_depth": gauge("ray_tpu_rl_post_trajectory_queue_depth"),
            "queue_bytes": gauge("ray_tpu_rl_post_trajectory_queue_bytes"),
            "generated_total": counter(
                "ray_tpu_rl_post_trajectories_generated_total"),
            "trained_total": counter(
                "ray_tpu_rl_post_trajectories_trained_total"),
            "dropped_total": counter(
                "ray_tpu_rl_post_trajectories_dropped_total"),
            "stale_dropped_total": counter(
                "ray_tpu_rl_post_trajectories_stale_total"),
            "publishes_total": counter("ray_tpu_rl_post_publishes_total"),
            "rollout_preemptions_total": counter(
                "ray_tpu_rl_post_rollout_preemptions_total"),
            "max_trained_staleness": gauge(
                "ray_tpu_rl_post_max_trained_staleness"),
        }

    def prefill_span_summary(self, agg: Optional[dict] = None) -> dict:
        """The measured prefill-span distribution + arrival rate the r20
        autoscaler sizes the prefill pool from. Mean comes from the
        merged histogram sum/count; the arrival rate is the per-second
        rate of the same histogram's cumulative count rings (every
        request that produced a first token counts exactly once)."""
        if agg is None:
            agg = self.cluster_metrics()
        name = _fq("llm_prefill_span_seconds")
        now_w = time.time()
        rate = 0.0
        with self._lock:
            for (_rid, nm, _tags), st in self._series.items():
                if nm == name:
                    rate += self._rate(st["ring"], now_w)
        count, total = 0, 0.0
        p95 = None
        acc = agg["histograms"].get(name)
        if acc:
            for merged in acc["series"].values():
                count += int(merged.get("count", 0))
                total += float(merged.get("sum", 0.0))
                p = merged.get("p95")
                if p is not None:
                    p95 = max(p95, p) if p95 is not None else p
        return {
            "count": count,
            "mean_s": round(total / count, 6) if count else None,
            "p95_s": p95,
            "arrival_rate_per_s": round(rate, 6),
        }

    def autoscale_signals(
        self, thresholds: Optional[SLOThresholds] = None
    ) -> dict:
        """Everything the PoolAutoscaler consumes, from ONE aggregation
        pass: per-tag grades + autoscaler_hints, pool rollups, queue
        depth, the prefill-span distribution, per-reporter staleness.
        Pending lease demand is GCS-side state and is layered on by
        ``gcs_service.rpc_autoscale_signals``."""
        agg = self.cluster_metrics()
        return {
            "ts_wall": agg["ts_wall"],
            "staleness": agg["staleness"],
            "slo": self.slo_report(thresholds, agg),
            "pools": self.pool_rollups(agg),
            "utilization": self.utilization(agg),
            "prefill_span": self.prefill_span_summary(agg),
        }

    def autoscale_health(self, agg: Optional[dict] = None) -> dict:
        """Controller health for `ray_tpu status`: decision mix, scale
        events, cold-start timings, current pool targets, and whether
        the controller is holding on a dark GCS. All None/empty when no
        controller is reporting."""
        if agg is None:
            agg = self.cluster_metrics()

        def counter_total(name):
            c = agg["counters"].get(_fq(name))
            return int(c["total"]) if c else None

        by_action: dict = {}
        acc = agg["counters"].get(_fq("ray_tpu_autoscale_decisions_total"))
        if acc:
            for skey, v in acc["series"].items():
                action = self._parse_tags_key(skey).get("action", "")
                by_action[action] = by_action.get(action, 0) + int(v)
        targets: dict = {}
        g = agg["gauges"].get(_fq("ray_tpu_autoscale_pool_target"))
        if g:
            for skey, v in g["series"].items():
                pool = self._parse_tags_key(skey).get("pool", "")
                targets[pool] = targets.get(pool, 0) + int(v)
        cold = {"count": 0, "p50_s": None, "p95_s": None}
        h = agg["histograms"].get(_fq("ray_tpu_autoscale_cold_start_seconds"))
        if h:
            for merged in h["series"].values():
                cold["count"] += int(merged.get("count", 0))
                for q in ("p50", "p95"):
                    p = merged.get(q)
                    if p is not None:
                        key = f"{q}_s"
                        cold[key] = (
                            max(cold[key], p) if cold[key] is not None else p
                        )
        dark = agg["gauges"].get(_fq("ray_tpu_autoscale_gcs_dark"))
        return {
            "decisions_total": counter_total("ray_tpu_autoscale_decisions_total"),
            "decisions_by_action": by_action,
            "scale_ups_total": counter_total("ray_tpu_autoscale_scale_ups_total"),
            "scale_downs_total": counter_total(
                "ray_tpu_autoscale_scale_downs_total"),
            "holds_total": counter_total("ray_tpu_autoscale_holds_total"),
            "pool_targets": targets,
            "cold_starts": cold,
            "gcs_dark": dark["value"] if dark else None,
        }

    def fleet_health(self, agg: Optional[dict] = None) -> dict:
        """Multi-tenant fleet rollup for `ray_tpu status` (r21): per-
        tenant request and shed counts (whether QoS isolation is pricing
        the right tenant), adapter slot churn (loads/evictions +
        residency per base model), canary rollout outcomes, and the
        preemption mix by reason (a paying tenant's priority preemptions
        show up here, not buried in engine pressure preemptions). All
        None/empty when no fleet is reporting."""
        if agg is None:
            agg = self.cluster_metrics()

        def counter_total(name):
            c = agg["counters"].get(_fq(name))
            return int(c["total"]) if c else None

        def by_tag(name, tag_name):
            acc = agg["counters"].get(_fq(name))
            out: dict = {}
            if acc:
                for skey, v in acc["series"].items():
                    key = self._parse_tags_key(skey).get(tag_name, "")
                    out[key] = out.get(key, 0) + int(v)
            return out

        resident: dict = {}
        g = agg["gauges"].get(_fq("ray_tpu_fleet_resident_adapters"))
        if g:
            for skey, v in g["series"].items():
                model = self._parse_tags_key(skey).get("model", "")
                resident[model] = resident.get(model, 0) + int(v)
        return {
            "tenant_requests": by_tag(
                "ray_tpu_fleet_tenant_requests_total", "tenant"),
            "rejections_by_tenant": {
                t: n for t, n in by_tag(
                    "ray_tpu_llm_admission_rejected_total", "tenant"
                ).items() if t
            },
            "adapter_loads_total": counter_total(
                "ray_tpu_fleet_adapter_loads_total"),
            "adapter_evictions_total": counter_total(
                "ray_tpu_fleet_adapter_evictions_total"),
            "resident_adapters_by_model": resident,
            "canary_by_outcome": by_tag(
                "ray_tpu_fleet_canary_rollouts_total", "outcome"),
            "preemptions_by_reason": by_tag(
                "ray_tpu_llm_preemptions_total", "reason"),
        }

    # regression-ratio grade ladder (latest/best sampled step time):
    # ≤ 1.25 green, ≤ 2.5 yellow, beyond red — mirrors the SLO grader's
    # threshold/yellow_factor shape
    PERF_REGRESSION_GREEN = 1.25
    PERF_REGRESSION_YELLOW_FACTOR = 2.0

    def perf_health(self, agg: Optional[dict] = None) -> dict:
        """Sampled-profiling rollup for `ray_tpu status` (r22): per-step
        latest step time, coverage, MFU, all-reduce overlap, and the
        regression ratio vs the best-seen sample — graded GREEN/YELLOW/
        RED so a slowly-regressing step is a status-line fact, not a
        future bench surprise. Includes the sampler's own duty receipt.
        All None/empty when no sampler is reporting."""
        if agg is None:
            agg = self.cluster_metrics()

        def gauge_by_step(name):
            g = agg["gauges"].get(_fq(name))
            out: dict = {}
            if g:
                for skey, v in g["series"].items():
                    step = self._parse_tags_key(skey).get("step", "")
                    out[step] = max(out[step], v) if step in out else v
            return out

        step_ms = gauge_by_step("ray_tpu_perf_step_ms")
        coverage = gauge_by_step("ray_tpu_perf_coverage_pct")
        mfu = gauge_by_step("ray_tpu_perf_mfu_pct")
        overlap = gauge_by_step("ray_tpu_perf_overlap_ratio")
        ratio = gauge_by_step("ray_tpu_perf_step_regression_ratio")
        samples: dict = {}
        c = agg["counters"].get(_fq("ray_tpu_perf_samples_total"))
        if c:
            for skey, v in c["series"].items():
                step = self._parse_tags_key(skey).get("step", "")
                samples[step] = samples.get(step, 0) + int(v)
        # worst-segment pointer per step from the merged histograms:
        # where is the sampled time actually going?
        top_segment: dict = {}
        h = agg["histograms"].get(_fq("ray_tpu_perf_segment_ms"))
        if h:
            for skey, merged in h["series"].items():
                tags = self._parse_tags_key(skey)
                step, seg = tags.get("step", ""), tags.get("segment", "")
                p95 = merged.get("p95")
                if p95 is None:
                    continue
                cur = top_segment.get(step)
                if cur is None or p95 > cur[1]:
                    top_segment[step] = (seg, p95)
        duty = agg["gauges"].get(_fq("ray_tpu_perf_sampler_duty_pct"))
        steps: dict = {}
        for step in sorted(set(step_ms) | set(ratio)):
            steps[step] = {
                "step_ms": step_ms.get(step),
                "coverage_pct": coverage.get(step),
                "mfu_pct": mfu.get(step),
                "overlap_ratio": overlap.get(step),
                "regression_ratio": ratio.get(step),
                "samples": samples.get(step, 0),
                "top_segment": top_segment.get(step),
                "grade": grade_value(
                    ratio.get(step),
                    self.PERF_REGRESSION_GREEN,
                    self.PERF_REGRESSION_YELLOW_FACTOR,
                ),
            }
        return {
            "steps": steps,
            "sampler_duty_pct": duty["value"] if duty else None,
        }

    def status_payload(self, thresholds: Optional[SLOThresholds] = None) -> dict:
        """Everything `ray_tpu status` needs beyond the node table — the
        GCS assembles this so the CLI is ONE RPC. The full aggregation
        pass (every series, under the lock) runs ONCE and feeds all
        eight views."""
        agg = self.cluster_metrics()
        return {
            "reporters": agg["reporters"],
            "staleness": agg["staleness"],
            "pools": self.pool_rollups(agg),
            "utilization": self.utilization(agg),
            "slo": self.slo_report(thresholds, agg),
            "trainer": self.trainer_health(agg),
            "fabric": self.fabric_health(agg),
            "kvtier": self.kvtier_health(agg),
            "rl_post": self.rl_post_health(agg),
            "autoscale": self.autoscale_health(agg),
            "fleet": self.fleet_health(agg),
            "perf": self.perf_health(agg),
        }


# -- `ray_tpu status` rendering ----------------------------------------------


def _fmt_bytes(n) -> str:
    if n is None:
        return "-"
    n = float(n)
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if n < 1024 or unit == "TB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}TB"


def _fmt_s(v) -> str:
    return "-" if v is None else f"{float(v) * 1e3:.1f}ms"


def format_status(report: dict) -> str:
    """Human-readable cluster status (the `ray_tpu status` output): nodes,
    pools, utilization, SLO grades — all from one GCS query."""
    lines: list[str] = []
    nodes = report.get("nodes", [])
    reporters = report.get("reporters", {})
    staleness = report.get("staleness", {})
    alive = [n for n in nodes if n.get("alive")]
    vals = [v for v in staleness.values() if v is not None]
    stale_max = max(vals) if vals else None
    lines.append(
        f"== nodes ({len(alive)}/{len(nodes)} alive, "
        f"{len(reporters)} reporters, "
        f"staleness max {stale_max if stale_max is not None else '-'}s) =="
    )
    for n in nodes:
        avail = n.get("available", {})
        total = n.get("resources", {})
        res = " ".join(
            f"{k}={avail.get(k, 0):g}/{total.get(k, 0):g}" for k in sorted(total)
        )
        state = "alive" if n.get("alive") else "DEAD"
        if n.get("draining"):
            state += ",draining"
        st = staleness.get(n.get("node_id"))
        lines.append(
            f"  {n.get('node_id', '?'):<16} {state:<14} {res}"
            + (f"  staleness={st}s" if st is not None else "  [no telemetry]")
        )
    pools = report.get("pools", {})
    lines.append("== pools ==")
    if pools:
        for role in sorted(pools):
            p = pools[role]
            lines.append(
                f"  role={role:<10} replicas "
                f"{p.get('replicas_running', 0)}/{p.get('replicas_target', 0)}"
                f"  deployments: {', '.join(p.get('deployments', [])) or '-'}"
            )
    else:
        lines.append("  (no serve pools reporting)")
    ft = report.get("gcs_ft") or {}
    ha = report.get("gcs_ha") or {}
    if ft.get("gcs_restarts_total") or ha:
        # the blackout must SHOW here: a restarted control plane renders
        # as a counted restart + reconcile deltas, not phantom-zero rows;
        # an HA pair renders its role/term/replication posture the same
        # way (a promoted standby is a counted failover, not a mystery)
        lines.append("== control plane ==")
        if ha:
            lag = ha.get("replication_lag_s")
            lines.append(
                f"  role {ha.get('role', '?')}  term {ha.get('term', 0)}"
                f"  replication lag "
                f"{f'{lag:.3f}s' if lag is not None else '-'}"
                f"  failovers {ha.get('failovers_total', 0)}"
                + (f"  fenced writes {ha['fenced_writes_total']}"
                   if ha.get("fenced_writes_total") else "")
                + ("  [FENCED]" if ha.get("fenced") else "")
            )
        if ft.get("gcs_restarts_total"):
            lines.append(
                f"  gcs restarts {ft['gcs_restarts_total']}"
                f"  reconcile: {ft.get('reconcile_nodes_reregistered', 0)} nodes"
                f", actors +{ft.get('reconcile_actors_confirmed', 0)} confirmed"
                f" +{ft.get('reconcile_actors_resurrected', 0)} resurrected"
                f" -{ft.get('reconcile_actors_lost', 0)} lost"
                f", bundles {ft.get('reconcile_bundles_adopted', 0)} adopted"
                f"/{ft.get('reconcile_bundles_orphaned', 0)} released"
                + (f"  [{ft['actors_pending_confirm']} awaiting confirm]"
                   if ft.get("actors_pending_confirm") else "")
            )
    trainer = report.get("trainer") or {}
    if any(v is not None for v in trainer.values()):
        ge = trainer.get("gang_epoch")
        rec = trainer.get("recoveries_total")
        lost = trainer.get("ranks_lost_total")
        lines.append("== trainer ==")
        lines.append(
            f"  gang epoch {int(ge) if ge is not None else '-'}"
            f"  recoveries {int(rec) if rec is not None else 0}"
            f"  ranks lost {int(lost) if lost is not None else 0}"
        )
    fabric = report.get("fabric") or {}
    if fabric.get("edges_by_backend"):
        # the transfer fabric must SHOW here: which edges ride the chip
        # interconnect vs the wire, and how many device edges have been
        # burned down to their RPC fallback
        eb = fabric["edges_by_backend"]
        total_edges = sum(eb.values())
        mix = " ".join(f"{b}={n}" for b, n in sorted(eb.items()) if n)
        lines.append("== fabric ==")
        line = f"  edges {total_edges} ({mix})"
        fb = fabric.get("fallbacks_total")
        line += f"  fallbacks {int(fb) if fb is not None else 0}"
        lines.append(line)
        bb = fabric.get("kv_bytes_by_backend") or {}
        if bb:
            lines.append(
                "  kv bytes " + " ".join(
                    f"{b}={_fmt_bytes(n)}" for b, n in sorted(bb.items()) if n
                )
            )
    kvt = report.get("kvtier") or {}
    if (kvt.get("resident_bytes_by_tier") or kvt.get("spilled_bytes_by_tier")
            or kvt.get("hit_tokens_by_tier")):
        # the tier ladder must SHOW here: how much spilled prefix cache
        # each deep tier holds, which tier is actually serving hits, and
        # whether any spilled copy ever failed its seal
        lines.append("== kv tiers ==")
        res = kvt.get("resident_bytes_by_tier") or {}
        lines.append(
            "  resident "
            + (" ".join(f"{t}={_fmt_bytes(n)}" for t, n in sorted(res.items()))
               or "-")
            + "  spilled "
            + (" ".join(
                f"{t}={_fmt_bytes(n)}"
                for t, n in sorted((kvt.get("spilled_bytes_by_tier")
                                    or {}).items()) if n) or "-")
        )
        hits = kvt.get("hit_tokens_by_tier") or {}
        if hits:
            line = "  hit tokens " + " ".join(
                f"{t}={int(n)}" for t, n in sorted(hits.items()) if n
            )
            cd = kvt.get("corrupt_dropped_total")
            if cd:
                line += f"  corrupt dropped {int(cd)}"
            lines.append(line)
        pf = kvt.get("prefetch") or {}
        fb = kvt.get("fetch_bytes_by_backend") or {}
        sq = kvt.get("spill_queue_depth")
        if pf.get("started") or fb or sq:
            # the r18 rungs must SHOW too: how far ahead of admission
            # prefetch runs, what crosses engines, what's still queued
            # for the async spill gather
            line = (
                f"  prefetch {int(pf.get('started', 0))} started"
                f" / {int(pf.get('completed', 0))} completed"
                f" / {int(pf.get('wasted', 0))} wasted"
            )
            if fb:
                line += "  fetched " + " ".join(
                    f"{b}={_fmt_bytes(n)}" for b, n in sorted(fb.items()) if n
                )
            if sq:
                line += f"  spill queue {int(sq)}"
            lines.append(line)
        idx = report.get("kvtier_index") or {}
        if idx.get("rows"):
            lines.append(
                f"  index {idx['rows']} rows / {idx['engines']} engines "
                f"({' '.join(f'{t}={n}' for t, n in sorted((idx.get('rows_by_tier') or {}).items()))})"
            )
    rp = report.get("rl_post") or {}
    if rp.get("version_by_tier") or rp.get("generated_total"):
        # actor/learner skew must SHOW here: which version each tier is
        # on, how many trajectories sit between them, and whether the
        # staleness contract dropped anything — from ONE RPC
        lines.append("== rl post-train ==")
        vb = rp.get("version_by_tier") or {}
        lv = vb.get("learner")
        rv = vb.get("rollout")
        skew = (
            int(lv - rv) if lv is not None and rv is not None else None
        )
        lines.append(
            "  weight version "
            + " ".join(f"{t}={int(v)}" for t, v in sorted(vb.items()))
            + (f"  skew {skew}" if skew is not None else "")
        )
        qd = rp.get("queue_depth")
        line = (
            f"  trajectories {int(rp.get('generated_total') or 0)} generated"
            f" / {int(rp.get('trained_total') or 0)} trained"
            f"  queue {int(qd) if qd is not None else '-'}"
        )
        if rp.get("queue_bytes"):
            line += f" ({_fmt_bytes(rp['queue_bytes'])})"
        dropped = rp.get("dropped_total") or 0
        stale = rp.get("stale_dropped_total") or 0
        if dropped or stale:
            line += f"  dropped {int(dropped)}  stale {int(stale)}"
        mts = rp.get("max_trained_staleness")
        if mts is not None:
            line += f"  max trained staleness {int(mts)}"
        lines.append(line)
        pub = rp.get("publishes_total")
        pre = rp.get("rollout_preemptions_total")
        if pub or pre:
            lines.append(
                f"  publishes {int(pub or 0)}"
                f"  rollout preemptions {int(pre or 0)}"
            )
    fl = report.get("fleet") or {}
    if fl.get("tenant_requests") or fl.get("adapter_loads_total"):
        # tenant isolation must SHOW here: who is actually being served,
        # who is being shed, who is being preempted for whom — plus the
        # adapter slot churn and canary rollout scoreboard
        lines.append("== fleet ==")
        tr = fl.get("tenant_requests") or {}
        rj = fl.get("rejections_by_tenant") or {}
        lines.append(
            "  tenants "
            + (" ".join(
                f"{t}={int(n)}"
                + (f"(-{int(rj[t])})" if rj.get(t) else "")
                for t, n in sorted(tr.items())
            ) or "-")
        )
        line = (
            f"  adapters loaded {int(fl.get('adapter_loads_total') or 0)}"
            f" / evicted {int(fl.get('adapter_evictions_total') or 0)}"
        )
        res = fl.get("resident_adapters_by_model") or {}
        if res:
            line += "  resident " + " ".join(
                f"{m}={int(n)}" for m, n in sorted(res.items())
            )
        lines.append(line)
        can = fl.get("canary_by_outcome") or {}
        pre = fl.get("preemptions_by_reason") or {}
        if can or pre:
            line = "  canary " + (
                " ".join(f"{o}={int(n)}" for o, n in sorted(can.items())
                         if n) or "-"
            )
            if pre:
                line += "  preemptions " + " ".join(
                    f"{r}={int(n)}" for r, n in sorted(pre.items()) if n
                )
            lines.append(line)
    asc = report.get("autoscale") or {}
    if asc.get("decisions_total"):
        lines.append("== autoscaler ==")
        by = asc.get("decisions_by_action") or {}
        lines.append(
            f"  decisions {int(asc['decisions_total'])}"
            f"  up {int(asc.get('scale_ups_total') or 0)}"
            f"  down {int(asc.get('scale_downs_total') or 0)}"
            f"  hold {int(asc.get('holds_total') or 0)}"
            + (
                "  (" + " ".join(
                    f"{a}={n}" for a, n in sorted(by.items()) if n
                ) + ")" if by else ""
            )
        )
        line = "  targets " + (
            " ".join(
                f"{p}={n}" for p, n in sorted(
                    (asc.get("pool_targets") or {}).items())
            ) or "-"
        )
        cold = asc.get("cold_starts") or {}
        if cold.get("count"):
            line += (
                f"  cold starts {int(cold['count'])}"
                f" (p50 {_fmt_s(cold.get('p50_s'))},"
                f" p95 {_fmt_s(cold.get('p95_s'))})"
            )
        dark = asc.get("gcs_dark")
        if dark:
            line += "  GCS DARK (holding)"
        lines.append(line)
    perf = report.get("perf") or {}
    if perf.get("steps"):
        # the sampled-profiling plane must SHOW here: per-step time,
        # regression grade vs best-seen, where the time goes, and the
        # sampler's own overhead receipt
        duty = perf.get("sampler_duty_pct")
        lines.append(
            "== perf (sampled) =="
            + (f"  duty {duty:.2f}%" if duty is not None else "")
        )
        for step in sorted(perf["steps"]):
            e = perf["steps"][step]
            sm = e.get("step_ms")
            cov = e.get("coverage_pct")
            mfu = e.get("mfu_pct")
            ov = e.get("overlap_ratio")
            rr = e.get("regression_ratio")
            top = e.get("top_segment")
            line = (
                f"  {step:<14} {e['grade'].upper():<7} "
                f"{sm:.2f}ms" if sm is not None
                else f"  {step:<14} {e['grade'].upper():<7} -"
            )
            if rr is not None:
                line += f" ({rr:.2f}x best)"
            if cov is not None:
                line += f"  coverage {cov:.1f}%"
            if mfu is not None:
                line += f"  mfu {mfu:.1f}%"
            if ov is not None:
                line += f"  overlap {ov:.2f}"
            if top:
                line += f"  top {top[0]}={top[1]:g}ms"
            line += f"  (n={e.get('samples', 0)})"
            lines.append(line)
    u = report.get("utilization", {})
    occ = u.get("kv_page_occupancy")
    lines.append("== utilization ==")
    lines.append(
        f"  kv pages {u.get('kv_pages_used', '-')}/{u.get('kv_pages_total', '-')}"
        + (f" ({occ * 100:.1f}%)" if occ is not None else "")
        + f"  hbm {_fmt_bytes(u.get('kv_hbm_bytes'))}"
        + f"  queue depth {u.get('queue_depth', '-')}"
        + f"  running {u.get('running_requests', '-')}"
    )
    rate = u.get("kv_transfer_bytes_per_s")
    accept = u.get("spec_acceptance_rate")
    lines.append(
        f"  kv transfer {_fmt_bytes(rate)}/s"
        + (f"  spec acceptance {accept:.2f}" if accept is not None else "")
    )
    slo = report.get("slo", {})
    th = slo.get("thresholds", {})
    pct = th.get("percentile", 95.0)
    lines.append(f"== SLO (p{pct:g} vs thresholds) ==")
    tags = slo.get("model_tags", {})
    if tags:
        for tag in sorted(tags):
            e = tags[tag]
            pk = f"p{pct:g}"
            lines.append(
                f"  {tag:<24} {e['grade'].upper():<7} "
                f"ttft {_fmt_s(e['ttft'].get(pk))} "
                f"tpot {_fmt_s(e['tpot'].get(pk))} "
                f"queue {_fmt_s(e['queue_wait'].get(pk))} "
                f"(n={e['ttft'].get('count', 0)})"
            )
    else:
        lines.append("  (no SLO histograms reporting)")
    return "\n".join(lines)
