"""Flight recorder: bounded in-process span store, last-N traces.

The serving analog of core/events.TaskEventBuffer: every instrumented
layer (OpenAI app, engine lifecycle, serve dispatch, replicas) records
``Span``s here keyed by trace_id. Capacity is bounded two ways —
``max_traces`` whole requests (drop-oldest, so a long-running server
always holds the most recent window) and ``max_spans_per_trace``
(a runaway generation cannot grow one trace without bound); drops are
counted, never silent.

Reads: ``get(trace_id)`` raw spans, ``traces()`` the flight-recorder
listing, ``summary(trace_id)`` e2e + span coverage honesty metrics,
``chrome_trace()`` Perfetto-ready events merged with the profiler/task
timeline by the dashboard ``/api/trace`` route.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import time
from collections import OrderedDict
from typing import Optional

from ray_tpu.obs import context as trace_context


@dataclasses.dataclass
class Span:
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    name: str
    start: float               # time.time() seconds
    end: float
    attrs: dict = dataclasses.field(default_factory=dict)
    status: str = "ok"         # ok | error

    @property
    def duration_s(self) -> float:
        return max(0.0, self.end - self.start)

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start": self.start,
            "end": self.end,
            "duration_s": round(self.duration_s, 6),
            "attrs": dict(self.attrs),
            "status": self.status,
        }


class SpanRecorder:
    """Thread-safe ring of the last ``max_traces`` traces."""

    def __init__(self, max_traces: int = 256, max_spans_per_trace: int = 512):
        self.max_traces = max_traces
        self.max_spans_per_trace = max_spans_per_trace
        self._lock = threading.Lock()
        self._traces: "OrderedDict[str, list[Span]]" = OrderedDict()
        self._meta: dict[str, dict] = {}
        self._by_request: dict[str, str] = {}  # request_id -> trace_id
        self.num_dropped_traces = 0
        self.num_dropped_spans = 0

    # -- writes ---------------------------------------------------------------

    def add(self, span: Span) -> None:
        with self._lock:
            spans = self._traces.get(span.trace_id)
            if spans is None:
                while len(self._traces) >= self.max_traces:
                    old_tid, _ = self._traces.popitem(last=False)
                    meta = self._meta.pop(old_tid, None)
                    for rid in (meta or {}).get("request_ids", ()):
                        self._by_request.pop(rid, None)
                    self.num_dropped_traces += 1
                spans = self._traces[span.trace_id] = []
                self._meta[span.trace_id] = {
                    "trace_id": span.trace_id,
                    "root": span.name,
                    "_root_dur": span.duration_s,
                    "start": span.start,
                    "end": span.end,
                    "num_spans": 0,
                    "request_ids": [],
                }
            meta = self._meta[span.trace_id]
            if len(spans) >= self.max_spans_per_trace:
                # drop-oldest WITHIN the trace too: the request-level root
                # spans (llm.request / api.*) are recorded LAST, at finish
                # — dropping the newest would lose exactly the spans the
                # /v1/requests surface and SLO attrs are keyed on
                del spans[0]
                self.num_dropped_spans += 1
            spans.append(span)
            meta["num_spans"] = len(spans)
            meta["start"] = min(meta["start"], span.start)
            meta["end"] = max(meta["end"], span.end)
            # the listing labels a trace by its widest span (matches
            # summary()'s root selection): llm.request / api.completions
            # rather than whichever phase span happened to land first
            if span.parent_id is None or span.duration_s >= meta["_root_dur"]:
                meta["root"] = span.name
                meta["_root_dur"] = span.duration_s
            rid = span.attrs.get("request_id")
            if rid is not None and rid not in meta["request_ids"]:
                meta["request_ids"].append(rid)
                self._by_request[str(rid)] = span.trace_id

    def record(
        self,
        name: str,
        start: float,
        end: float,
        ctx: Optional[trace_context.TraceContext] = None,
        *,
        attrs: Optional[dict] = None,
        status: str = "ok",
    ) -> Optional[Span]:
        """Record one completed span under ``ctx`` (the span becomes a
        CHILD of ctx.span_id). Without a ctx the span starts its own
        trace. The explicit-ctx API exists for threads that don't carry
        the contextvar (the engine loop records against each Request's
        stored context)."""
        if ctx is None:
            ctx = trace_context.current() or trace_context.new_context()
        span = Span(
            trace_id=ctx.trace_id,
            span_id=trace_context._rand_hex(8),
            parent_id=ctx.span_id,
            name=name,
            start=start,
            end=end,
            attrs=dict(attrs or {}),
            status=status,
        )
        self.add(span)
        return span

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()
            self._meta.clear()
            self._by_request.clear()
            self.num_dropped_traces = 0
            self.num_dropped_spans = 0

    # -- reads ----------------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._traces)

    def get(self, trace_id: str) -> list[Span]:
        with self._lock:
            return list(self._traces.get(trace_id, ()))

    def find_by_request(self, request_id: str) -> Optional[str]:
        with self._lock:
            return self._by_request.get(str(request_id))

    def traces(self, limit: int = 100) -> list[dict]:
        """Flight-recorder listing, newest first."""
        with self._lock:
            metas = [
                {k: v for k, v in m.items() if not k.startswith("_")}
                for m in self._meta.values()
            ]
        metas.sort(key=lambda m: m["start"], reverse=True)
        for m in metas[:limit]:
            m["duration_s"] = round(max(0.0, m["end"] - m["start"]), 6)
        return metas[:limit]

    def summary(self, trace_id: str) -> Optional[dict]:
        """Root span + coverage honesty: % of the root's wall-clock
        covered by the union of its descendant spans (the profiler's
        coverage_pct idea applied to one request)."""
        spans = self.get(trace_id)
        if not spans:
            return None
        ids = {s.span_id for s in spans}
        roots = [s for s in spans if s.parent_id is None or s.parent_id not in ids]
        # widest orphan wins: engine-only traces have no API root span, so
        # every lifecycle span is parentless — the request-covering
        # llm.request span is the one coverage should be measured against
        root = max(roots or spans, key=lambda s: s.duration_s)
        children = [s for s in spans if s is not root]
        coverage = 0.0
        if root.duration_s > 0 and children:
            intervals = sorted(
                (max(s.start, root.start), min(s.end, root.end))
                for s in children
            )
            covered, cur_a, cur_b = 0.0, None, None
            for a, b in intervals:
                if b <= a:
                    continue
                if cur_b is None or a > cur_b:
                    if cur_b is not None:
                        covered += cur_b - cur_a
                    cur_a, cur_b = a, b
                else:
                    cur_b = max(cur_b, b)
            if cur_b is not None:
                covered += cur_b - cur_a
            coverage = 100.0 * covered / root.duration_s
        return {
            "trace_id": trace_id,
            "root": root.name,
            "start": root.start,
            "e2e_s": round(root.duration_s, 6),
            "num_spans": len(spans),
            "coverage_pct": round(coverage, 2),
            "attrs": dict(root.attrs),
        }

    # default export cap: ~200 bytes/event keeps the largest export well
    # under RPC framing / HTTP response sanity (a full recorder at
    # 256 traces x 512 spans is 131k spans ≈ tens of MB otherwise)
    DEFAULT_EXPORT_MAX_EVENTS = 50_000

    def chrome_trace(self, trace_id: Optional[str] = None,
                     max_events: Optional[int] = None) -> list[dict]:
        """Chrome trace-event JSON ("X" complete events); rows grouped
        by trace so one request reads as one strip in Perfetto.
        ``max_events`` caps the export (earliest-first after a time sort);
        use :meth:`chrome_trace_bounded` to also learn whether the cap
        bit."""
        return self.chrome_trace_bounded(
            trace_id=trace_id, max_events=max_events
        )["events"]

    def chrome_trace_bounded(self, trace_id: Optional[str] = None,
                             max_events: Optional[int] = None) -> dict:
        """Bounded export: {"events", "truncated", "total_spans"}. A large
        trace must not produce an export that blows past the cluster RPC
        MAX_FRAME guard (or an HTTP response nobody can open) — the cap
        drops the NEWEST events after an ascending time sort and says so
        instead of silently shipping everything."""
        cap = (self.DEFAULT_EXPORT_MAX_EVENTS
               if max_events is None else int(max_events))
        with self._lock:
            if trace_id is not None:
                groups = {trace_id: list(self._traces.get(trace_id, ()))}
            else:
                groups = {tid: list(sp) for tid, sp in self._traces.items()}
        out = []
        for tid, spans in groups.items():
            for s in spans:
                out.append({
                    "name": s.name,
                    "cat": "request" if s.status == "ok" else "request_error",
                    "ph": "X",
                    "ts": s.start * 1e6,
                    "dur": s.duration_s * 1e6,
                    "pid": f"trace:{tid[:8]}",
                    "tid": s.name.split(".")[0],
                    "args": {
                        "trace_id": s.trace_id,
                        "span_id": s.span_id,
                        "parent_id": s.parent_id,
                        **s.attrs,
                    },
                })
        total = len(out)
        truncated = cap >= 0 and total > cap
        if truncated:
            out.sort(key=lambda e: e["ts"])
            out = out[:cap]
        return {"events": out, "truncated": truncated, "total_spans": total}


_RECORDER = SpanRecorder()


def get_recorder() -> SpanRecorder:
    return _RECORDER


@contextlib.contextmanager
def span(name: str, attrs: Optional[dict] = None,
         recorder: Optional[SpanRecorder] = None):
    """Record a span around a block, propagating the contextvar: the
    block runs under a child context, so nested spans (and anything that
    serializes the ambient context into an envelope) chain correctly.
    Yields the child TraceContext."""
    parent = trace_context.current()
    ctx = parent.child() if parent is not None else trace_context.new_context()
    token = trace_context.attach(ctx)
    t0 = time.time()
    status = "ok"
    try:
        yield ctx
    except BaseException:
        status = "error"
        raise
    finally:
        try:
            trace_context.detach(token)
        except ValueError:
            # unwound in a different Context (async-generator finalized
            # by the loop in a fresh task); still record the span below
            pass
        rec = recorder if recorder is not None else _RECORDER
        rec.add(Span(
            trace_id=ctx.trace_id,
            span_id=ctx.span_id,
            parent_id=parent.span_id if parent is not None else None,
            name=name,
            start=t0,
            end=time.time(),
            attrs=dict(attrs or {}),
            status=status,
        ))
