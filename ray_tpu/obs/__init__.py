"""ray_tpu.obs — end-to-end request tracing + flight recorder + SLO metrics.

Three pieces:

 * context — ``TraceContext`` (W3C-traceparent shaped), carried by
   contextvar within a process and serialized into TaskSpecs, cluster
   RPC envelopes, and serve dispatch so one trace_id follows a request
   across API -> router -> engine -> cluster workers;
 * recorder — ``SpanRecorder``, a bounded flight recorder of the last N
   requests' spans (``obs.span(...)`` records + propagates in one call);
 * slo — serving SLO histograms (TTFT / TPOT / queue-wait / e2e +
   router dispatch latency) on the util/metrics Prometheus registry;
 * telemetry — the CLUSTER-WIDE metrics plane (import
   ``ray_tpu.obs.telemetry`` directly): per-process registries ship
   monotonic snapshots to the GCS (heartbeat piggyback / telemetry_push),
   which serves counter sums, bucket-merged histogram percentiles,
   role/pool rollups, SLO grades, a merged Prometheus exposition, and
   the ``scripts/ray_tpu_status.py`` one-query status report.

Instrumented surfaces: ``GET /api/trace`` on the dashboard (request
spans merged with the task/profiler timeline), ``GET /v1/requests`` +
``GET /v1/requests/{rid}/trace`` on the OpenAI app, and
``llm_serving_bench.py --trace``.
"""

from ray_tpu.obs.context import (
    TraceContext,
    attach,
    current,
    detach,
    new_context,
    use,
)
from ray_tpu.obs.recorder import Span, SpanRecorder, get_recorder, span

__all__ = [
    "TraceContext",
    "attach",
    "current",
    "detach",
    "new_context",
    "use",
    "Span",
    "SpanRecorder",
    "get_recorder",
    "span",
]
