"""Serving SLO metrics: TTFT / TPOT / queue-wait / e2e histograms.

The two numbers TPU-serving papers report (TTFT, TPOT) plus the two the
scheduler needs (queue_wait prices admission, e2e prices the whole
path), exported through the process-wide util/metrics registry so the
dashboard ``/metrics`` route serves them with zero extra plumbing.

Metric objects are constructed per call rather than cached: same-name
re-registration shares storage in util/metrics, and re-constructing
means a test's ``clear_registry()`` can never strand a stale cached
instance writing to storage the exporter no longer renders. These fire
once per REQUEST (and once per dispatch), not per token — the registry
lock is not a hot-path cost here.
"""

from __future__ import annotations

from typing import Optional

from ray_tpu.util.metrics import Histogram

# TTFT/queue-wait: sub-ms on a CPU smoke model, multi-second under a
# remote-compile tunnel or heavy admission queueing.
_TTFT_BOUNDARIES = [
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5,
    10, 30,
]
# TPOT: per-token decode latency; the HBM roofline puts a well-fed TPU
# decode in single-digit ms, a dispatch-bound CPU step in the tens.
_TPOT_BOUNDARIES = [
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1,
]
_E2E_BOUNDARIES = [
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60, 120,
]
_DISPATCH_BOUNDARIES = [
    0.00001, 0.00005, 0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1,
]


def ttft_histogram() -> Histogram:
    return Histogram(
        "llm_ttft_seconds",
        description="serving SLO: time to first token (request arrival -> "
        "first sampled token), seconds",
        boundaries=_TTFT_BOUNDARIES,
        tag_keys=("model",),
    )


def tpot_histogram() -> Histogram:
    return Histogram(
        "llm_tpot_seconds",
        description="serving SLO: time per output token after the first "
        "(decode steady state), seconds",
        boundaries=_TPOT_BOUNDARIES,
        tag_keys=("model",),
    )


def prefill_span_histogram() -> Histogram:
    return Histogram(
        "llm_prefill_span_seconds",
        description="prefill service span (first prefill dispatch -> "
        "first sampled token), seconds — the per-request prefill cost "
        "the r20 autoscaler sizes the prefill pool from",
        boundaries=_TTFT_BOUNDARIES,
        tag_keys=("model",),
    )


def queue_wait_histogram() -> Histogram:
    return Histogram(
        "llm_queue_wait_seconds",
        description="serving SLO: request arrival -> first prefill dispatch "
        "(admission queue wait), seconds",
        boundaries=_TTFT_BOUNDARIES,
        tag_keys=("model",),
    )


def e2e_histogram() -> Histogram:
    return Histogram(
        "llm_e2e_seconds",
        description="serving SLO: request arrival -> finish, seconds",
        boundaries=_E2E_BOUNDARIES,
        tag_keys=("model", "finish_reason"),
    )


_KV_TRANSFER_BOUNDARIES = [
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1,
    2.5, 5,
]


def kv_transfer_histogram() -> Histogram:
    return Histogram(
        "llm_kv_transfer_seconds",
        description="disaggregated serving: prefill-side export -> "
        "decode-side import complete for one KV handoff, seconds, by "
        "transport backend (inproc/rpc/device)",
        boundaries=_KV_TRANSFER_BOUNDARIES,
        tag_keys=("model", "backend"),
    )


def kv_transfer_bytes_counter():
    from ray_tpu.util.metrics import Counter

    return Counter(
        "llm_kv_transfer_bytes_total",
        description="disaggregated serving: KV page bytes moved "
        "prefill -> decode, by transport backend (inproc/rpc/device)",
        tag_keys=("model", "backend"),
    )


def router_dispatch_histogram() -> Histogram:
    return Histogram(
        "serve_router_dispatch_seconds",
        description="serve: router time to place one request on a replica "
        "(refresh + pick + submit), seconds",
        boundaries=_DISPATCH_BOUNDARIES,
        tag_keys=("app", "deployment"),
    )


def register_all() -> None:
    """Force-register every SLO metric (scripts/check_metrics.py hook —
    lazy construction would otherwise hide them from the static pass)."""
    ttft_histogram()
    tpot_histogram()
    prefill_span_histogram()
    queue_wait_histogram()
    e2e_histogram()
    router_dispatch_histogram()
    kv_transfer_histogram()
    kv_transfer_bytes_counter()


def record_request_slo(
    model: str,
    *,
    ttft_s: Optional[float],
    tpot_s: Optional[float],
    queue_wait_s: Optional[float],
    e2e_s: float,
    finish_reason: str,
    prefill_span_s: Optional[float] = None,
) -> None:
    """One finished request's SLO observations. Observability must never
    break serving: failures are swallowed."""
    try:
        tags = {"model": model}
        if ttft_s is not None:
            ttft_histogram().observe(ttft_s, tags=tags)
        if tpot_s is not None:
            tpot_histogram().observe(tpot_s, tags=tags)
        if queue_wait_s is not None:
            queue_wait_histogram().observe(queue_wait_s, tags=tags)
        if prefill_span_s is not None:
            prefill_span_histogram().observe(prefill_span_s, tags=tags)
        e2e_histogram().observe(
            e2e_s, tags={"model": model, "finish_reason": finish_reason or ""}
        )
    except Exception:  # noqa: BLE001
        pass


def record_kv_transfer(model: str, backend: str, *, seconds: float,
                       nbytes: int) -> None:
    """One completed KV handoff (disaggregated serving), labelled by
    the transport backend that carried it (inproc/rpc/device)."""
    try:
        tags = {"model": model, "backend": backend}
        kv_transfer_histogram().observe(seconds, tags=tags)
        kv_transfer_bytes_counter().inc(max(0, int(nbytes)), tags=tags)
    except Exception:  # noqa: BLE001
        pass


def record_dispatch(app: str, deployment: str, seconds: float) -> None:
    try:
        router_dispatch_histogram().observe(
            seconds, tags={"app": app, "deployment": deployment}
        )
    except Exception:  # noqa: BLE001
        pass
