"""Request-scoped trace context (W3C traceparent shaped).

A ``TraceContext`` is (trace_id, span_id): the trace_id names one
end-to-end request, the span_id names the current operation within it.
It travels three ways:

 * contextvar — within a thread / asyncio task (``use``/``attach``);
 * dict — inside RPC envelopes and TaskSpecs (``to_dict``/``from_dict``),
   pickle-free so it crosses the cluster plane unchanged;
 * header — ``traceparent: 00-<trace>-<span>-01`` for HTTP interop.

Deliberately dependency-free: core/, cluster/ and serve/ all import it
on their hot paths.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import os
import re
from typing import Optional

_TRACEPARENT_RE = re.compile(
    r"^(?P<version>[0-9a-f]{2})-(?P<trace_id>[0-9a-f]{32})"
    r"-(?P<span_id>[0-9a-f]{16})-(?P<flags>[0-9a-f]{2})$"
)


def _rand_hex(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


@dataclasses.dataclass(frozen=True)
class TraceContext:
    trace_id: str          # 32 lowercase hex chars (16 bytes)
    span_id: str           # 16 lowercase hex chars (8 bytes)
    sampled: bool = True

    def child(self) -> "TraceContext":
        """Same trace, fresh span id — the context a sub-operation runs
        under (its spans record this span as parent)."""
        return TraceContext(self.trace_id, _rand_hex(8), self.sampled)

    # -- wire formats ---------------------------------------------------------

    def to_dict(self) -> dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "sampled": self.sampled}

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> Optional["TraceContext"]:
        if not d or not d.get("trace_id"):
            return None
        return cls(
            trace_id=str(d["trace_id"]),
            span_id=str(d.get("span_id") or _rand_hex(8)),
            sampled=bool(d.get("sampled", True)),
        )

    def to_traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-{'01' if self.sampled else '00'}"

    @classmethod
    def from_traceparent(cls, header: Optional[str]) -> Optional["TraceContext"]:
        if not header:
            return None
        m = _TRACEPARENT_RE.match(header.strip().lower())
        if m is None:
            return None
        return cls(
            trace_id=m.group("trace_id"),
            span_id=m.group("span_id"),
            sampled=bool(int(m.group("flags"), 16) & 1),
        )


_CURRENT: contextvars.ContextVar[Optional[TraceContext]] = contextvars.ContextVar(
    "ray_tpu_trace_context", default=None
)


def current() -> Optional[TraceContext]:
    return _CURRENT.get()


def new_context() -> TraceContext:
    """Fresh root: new trace_id + span_id."""
    return TraceContext(_rand_hex(16), _rand_hex(8))


def attach(ctx: Optional[TraceContext]):
    """Set the ambient context; returns a token for ``detach``."""
    return _CURRENT.set(ctx)


def detach(token) -> None:
    _CURRENT.reset(token)


@contextlib.contextmanager
def use(ctx: Optional[TraceContext]):
    token = _CURRENT.set(ctx)
    try:
        yield ctx
    finally:
        try:
            _CURRENT.reset(token)
        except ValueError:
            # unwound in a different Context (e.g. an abandoned async
            # generator finalized by the event loop in a fresh task);
            # that transient context dies anyway — nothing to restore
            pass


@contextlib.contextmanager
def use_from(trace_dict: Optional[dict]):
    """Attach a serialized context around an execution body — the one
    helper every task-execution plane (thread scheduler, actor runtimes,
    cluster workers) wraps with. The context is attached AS-IS, not as a
    fresh child: the envelope's span_id names a span the SUBMITTER
    records (serve.request, an obs.span block), so spans recorded inside
    the body parent to a span that actually exists in the recorder — a
    per-execution child id would leave them dangling off a span nobody
    recorded. No-ops when the envelope carries no (valid) trace, and
    never raises: tracing must never break task execution. Yields the
    attached context or None."""
    try:
        ctx = TraceContext.from_dict(trace_dict)
    except Exception:  # noqa: BLE001
        ctx = None
    if ctx is None:
        yield None
        return
    with use(ctx):
        yield ctx
