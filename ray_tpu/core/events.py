"""Task event buffer: lifecycle records for observability.

Reference analog: src/ray/core_worker/task_event_buffer.h (batched task
state transitions) feeding GcsTaskManager
(src/ray/gcs/gcs_server/gcs_task_manager.h), which powers `ray list
tasks`, `ray timeline`, and the dashboard task table. Single-host: a
bounded ring buffer on the runtime, read by ray_tpu.util.state and the
timeline exporter.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Optional


class TaskState:
    SUBMITTED = "SUBMITTED"
    RUNNING = "RUNNING"
    FINISHED = "FINISHED"
    FAILED = "FAILED"


@dataclass
class TaskEvent:
    task_id: str
    name: str
    state: str
    ts: float
    kind: str = "task"          # task | actor_task
    actor_id: Optional[str] = None
    error: Optional[str] = None
    worker: str = ""            # thread name / worker pid
    # request tracing (ray_tpu.obs): set when the task ran under a
    # TraceContext, so timeline() nests cluster work under the request
    trace_id: Optional[str] = None
    span_id: Optional[str] = None


class TaskEventBuffer:
    """Bounded ring of task lifecycle events + live task table."""

    def __init__(self, max_events: int = 10_000):
        self._events: deque[TaskEvent] = deque(maxlen=max_events)
        self._lock = threading.Lock()
        # task_id -> latest state + name (live table; FINISHED/FAILED kept
        # until overwritten by ring pressure)
        self._latest: dict[str, TaskEvent] = {}
        self._max_latest = max_events

    def record(
        self,
        task_id,
        name: str,
        state: str,
        *,
        kind: str = "task",
        actor_id=None,
        error: Optional[str] = None,
        worker: str = "",
        ts: Optional[float] = None,
        trace_id: Optional[str] = None,
        span_id: Optional[str] = None,
    ) -> None:
        if trace_id is None:
            # auto-capture the ambient trace context: execution paths
            # attach the submitter's context around the task body, so
            # every record() call site tags events without plumbing
            from ray_tpu.obs import context as _trace_context

            ctx = _trace_context.current()
            if ctx is not None:
                trace_id, span_id = ctx.trace_id, ctx.span_id
        # explicit ts: reconstructed spans (profiler segment attribution)
        # land at their measured offsets instead of the record() call time
        ev = TaskEvent(
            task_id=str(task_id),
            name=name,
            state=state,
            ts=time.time() if ts is None else ts,
            kind=kind,
            actor_id=str(actor_id) if actor_id is not None else None,
            error=error,
            worker=worker or threading.current_thread().name,
            trace_id=trace_id,
            span_id=span_id,
        )
        with self._lock:
            self._events.append(ev)
            if len(self._latest) >= self._max_latest and ev.task_id not in self._latest:
                # bound memory strictly: evict a terminal entry if any
                # exists, else the oldest entry outright
                victim = None
                oldest = None
                for k, v in self._latest.items():
                    if v.state in (TaskState.FINISHED, TaskState.FAILED):
                        victim = k
                        break
                    if oldest is None or v.ts < self._latest[oldest].ts:
                        oldest = k
                del self._latest[victim if victim is not None else oldest]
            self._latest[ev.task_id] = ev

    def events(self, limit: int = 1000) -> list[TaskEvent]:
        with self._lock:
            evs = list(self._events)
        return evs[-limit:]

    def tasks(self, state: Optional[str] = None, limit: int = 1000) -> list[TaskEvent]:
        with self._lock:
            rows = list(self._latest.values())
        if state:
            rows = [r for r in rows if r.state == state]
        rows.sort(key=lambda r: r.ts, reverse=True)
        return rows[:limit]

    def chrome_trace(self, limit: int = 10_000) -> list[dict]:
        """Chrome trace-event JSON (reference: `ray timeline`)."""
        with self._lock:
            evs = list(self._events)[-limit:]
        spans: dict[str, dict] = {}
        out = []
        for ev in evs:
            if ev.state == TaskState.RUNNING:
                spans[ev.task_id] = {"start": ev.ts, "ev": ev}
            elif ev.state in (TaskState.FINISHED, TaskState.FAILED):
                span = spans.pop(ev.task_id, None)
                if span is None:
                    continue
                tid_ = ev.trace_id or span["ev"].trace_id
                sid = ev.span_id or span["ev"].span_id
                out.append(
                    {
                        "name": ev.name,
                        "cat": ev.kind,
                        "ph": "X",
                        "ts": span["start"] * 1e6,
                        "dur": (ev.ts - span["start"]) * 1e6,
                        "pid": 0,
                        "tid": span["ev"].worker,
                        "args": {
                            "task_id": ev.task_id,
                            "state": ev.state,
                            **({"error": ev.error} if ev.error else {}),
                            **({"trace_id": tid_, "span_id": sid} if tid_ else {}),
                        },
                    }
                )
        return out
