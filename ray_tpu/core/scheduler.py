"""Local task scheduling: dependency resolution + resource-gated dispatch.

Analog of the reference raylet's ClusterTaskManager/LocalTaskManager pair
(src/ray/raylet/scheduling/cluster_task_manager.h, local_task_manager.cc:94
ScheduleAndDispatchTasks) collapsed for the single-host case, with one
deliberate inversion: the reference leases *worker processes* because CPU
Python needs process isolation; a TPU host wants ONE JAX process, so the
default execution vehicle is a thread inside the host process (zero-copy
args, shared jit cache, chips stay owned by one process). Process workers
remain available (`worker_mode="process"`) for CPU-heavy Python tasks and
for crash-isolation semantics (retries on worker death).
"""

from __future__ import annotations

import threading
import traceback
from collections import deque
from typing import TYPE_CHECKING, Optional

from ray_tpu.core import errors
from ray_tpu.core.object_store import serialize
from ray_tpu.core.ref import ObjectRef, ObjectRefGenerator
from ray_tpu.core.resources import NodeResources, ResourceSet
from ray_tpu.core.task import TaskSpec
from ray_tpu.utils.ids import ObjectID
from ray_tpu.utils.logging import get_logger

if TYPE_CHECKING:
    from ray_tpu.core.runtime import Runtime

logger = get_logger("ray_tpu.scheduler")


def resolve_pool(
    runtime: "Runtime", options, default_pool: Optional[NodeResources] = None
) -> tuple[NodeResources, ResourceSet]:
    """Resolve the resource pool a task/actor draws from: its placement-group
    bundle if one is attached (directly or via scheduling strategy), else the
    node pool. Single source of truth for tasks AND actor creation."""
    req = options.resource_set()
    pg = options.placement_group
    strategy = options.scheduling_strategy
    if strategy is not None and hasattr(strategy, "placement_group"):
        pg = strategy.placement_group
        idx = strategy.placement_group_bundle_index
    else:
        idx = options.placement_group_bundle_index
    if strategy is not None and hasattr(strategy, "node_id") and pg is None:
        # NodeAffinity against the single-node runtime: the only node is
        # runtime.node_id — a hard affinity to any other node must FAIL
        # the task, not silently run it here (reference semantics:
        # unschedulable hard affinity raises, scheduling_strategies.py)
        nid = strategy.node_id
        local = runtime.node_id
        matches = (
            nid == local
            or (isinstance(nid, str) and nid == local.hex())
            or (isinstance(nid, bytes) and nid == local.binary())
        )
        if nid is not None and not matches and not getattr(strategy, "soft", False):
            raise errors.RayTpuError(
                f"NodeAffinitySchedulingStrategy(node_id={nid!r}, soft=False): "
                f"no such node in this runtime (local node {runtime.node_id})"
            )
    if pg is not None:
        return pg.bundle_pool(idx, req), req
    return default_pool if default_pool is not None else runtime.node_resources, req


class LocalScheduler:
    """FIFO-with-skipping dispatch over a resource pool (the hybrid policy's
    local leg; multi-node spillback slots in at `_pool_for`)."""

    def __init__(self, runtime: "Runtime", node_resources: NodeResources):
        self._runtime = runtime
        self._node = node_resources
        self._queue: deque[TaskSpec] = deque()
        self._cv = threading.Condition()
        self._shutdown = False
        self._running: dict = {}  # task_id -> spec
        self._dispatch_thread = threading.Thread(
            target=self._dispatch_loop, name="ray_tpu-dispatch", daemon=True
        )
        self._dispatch_thread.start()

    # -- submission ----------------------------------------------------------

    def submit(self, spec: TaskSpec) -> None:
        deps = self._collect_deps(spec)
        if not deps:
            self._enqueue(spec)
            return
        remaining = {"n": len(deps)}
        lock = threading.Lock()

        def _dep_ready(_obj_id: ObjectID) -> None:
            with lock:
                remaining["n"] -= 1
                if remaining["n"] != 0:
                    return
            self._enqueue(spec)

        for dep in deps:
            self._runtime.object_store.wait_async(dep, _dep_ready)

    def _collect_deps(self, spec: TaskSpec) -> list[ObjectID]:
        deps = []
        for a in list(spec.args) + list(spec.kwargs.values()):
            if isinstance(a, ObjectRef):
                deps.append(a.id)
        return deps

    def _enqueue(self, spec: TaskSpec) -> None:
        with self._cv:
            self._queue.append(spec)
            self._cv.notify_all()

    # -- dispatch ------------------------------------------------------------

    def _pool_for(self, spec: TaskSpec) -> tuple[NodeResources, ResourceSet]:
        return resolve_pool(self._runtime, spec.options, default_pool=self._node)

    def _dispatch_loop(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._shutdown:
                    self._cv.wait(timeout=0.2)
                if self._shutdown:
                    return
                # scan for the first task whose resources fit (skip blocked
                # heads: small tasks shouldn't starve behind a big one)
                picked: Optional[TaskSpec] = None
                pool = req = None
                for i, spec in enumerate(self._queue):
                    try:
                        pool, req = self._pool_for(spec)
                    except errors.RayTpuError as e:
                        del self._queue[i]
                        self._fail_task(spec, e)
                        self._runtime.on_task_finished(spec)
                        picked = None
                        break
                    if pool.try_acquire(req):
                        picked = spec
                        del self._queue[i]
                        break
                if picked is None:
                    # nothing fits right now; wait for a release/notify
                    self._cv.wait(timeout=0.05)
                    continue
            self._launch(picked, pool, req)

    def notify(self) -> None:
        with self._cv:
            self._cv.notify_all()

    def _launch(self, spec: TaskSpec, pool: NodeResources, req: ResourceSet) -> None:
        self._running[spec.task_id] = spec

        def _run():
            try:
                execute_task(self._runtime, spec)
            finally:
                self._running.pop(spec.task_id, None)
                pool.release(req)
                self.notify()

        if self._runtime.worker_mode == "process" and spec.actor_id is None:
            target = lambda: self._run_in_process(spec, pool, req)
            t = threading.Thread(target=target, name=f"ray_tpu-proxy-{spec.describe()}", daemon=True)
        else:
            t = threading.Thread(target=_run, name=f"ray_tpu-{spec.describe()}", daemon=True)
        t.start()

    # -- process-mode execution (crash isolation + retries) -----------------

    def _run_in_process(self, spec: TaskSpec, pool: NodeResources, req: ResourceSet) -> None:
        from ray_tpu.obs import context as trace_context

        with trace_context.use_from(spec.trace):
            return self._run_in_process_body(spec, pool, req)

    def _run_in_process_body(self, spec: TaskSpec, pool: NodeResources,
                             req: ResourceSet) -> None:
        from ray_tpu.core.events import TaskState

        runtime = self._runtime
        finished = True
        runtime.task_events.record(spec.task_id, spec.describe(), TaskState.RUNNING)
        try:
            try:
                result = runtime.process_pool.run(spec)
            except errors.WorkerCrashedError as e:
                if spec.attempt < spec.options.max_retries:
                    spec.attempt += 1
                    logger.warning(
                        "%s: worker crashed, retry %d/%d",
                        spec.describe(), spec.attempt, spec.options.max_retries,
                    )
                    finished = False
                    self._enqueue(spec)
                    return
                self._fail_task(spec, e)
                return
            except errors.TaskError as e:
                if spec.options.retry_exceptions and spec.attempt < spec.options.max_retries:
                    spec.attempt += 1
                    finished = False
                    self._enqueue(spec)
                    return
                self._fail_task(spec, e)
                return
            except BaseException as e:  # noqa: BLE001
                self._fail_task(
                    spec,
                    errors.TaskError(e, traceback.format_exc(), spec.describe()),
                )
                return
            _store_results(runtime, spec, result)
            runtime.task_events.record(
                spec.task_id, spec.describe(), TaskState.FINISHED
            )
        finally:
            self._running.pop(spec.task_id, None)
            pool.release(req)
            self.notify()
            if finished:
                runtime.on_task_finished(spec)

    def _fail_task(self, spec: TaskSpec, err: BaseException) -> None:
        """Store the error on all returns (caller handles on_task_finished)."""
        from ray_tpu.core.events import TaskState

        self._runtime.task_events.record(
            spec.task_id, spec.describe(), TaskState.FAILED, error=repr(err)
        )
        for rid in spec.return_ids:
            self._runtime.object_store.put_error(rid, err)
        gen = self._runtime.streaming_generators.pop(spec.task_id, None)
        if gen is not None:
            # surface the failure to the consumer as an error-carrying ref
            # (a bare _finish() would look like a clean empty stream)
            gen._append(ObjectRef(spec.return_ids[0], self._runtime, spec.describe()))
            gen._finish()

    def queue_depth(self) -> int:
        with self._cv:
            return len(self._queue)

    def shutdown(self) -> None:
        with self._cv:
            self._shutdown = True
            self._cv.notify_all()


# ---------------------------------------------------------------------------
# In-thread task execution (the TPU-host fast path).
# ---------------------------------------------------------------------------


def resolve_args(runtime: "Runtime", args: tuple, kwargs: dict) -> tuple[tuple, dict]:
    def res(a):
        if isinstance(a, ObjectRef):
            return runtime.object_store.get(a.id)
        return a

    return tuple(res(a) for a in args), {k: res(v) for k, v in kwargs.items()}


def execute_task(runtime: "Runtime", spec: TaskSpec) -> None:
    """Run a task inline on the current thread and store its results.

    Runs under the submitter's trace context (when the spec carries
    one): task events carry the caller's trace/span ids, nested submits
    chain further."""
    from ray_tpu.obs import context as trace_context

    with trace_context.use_from(spec.trace):
        return _execute_task_body(runtime, spec)


def _execute_task_body(runtime: "Runtime", spec: TaskSpec) -> None:
    from ray_tpu.core.events import TaskState

    runtime.task_events.record(spec.task_id, spec.describe(), TaskState.RUNNING)
    try:
        args, kwargs = resolve_args(runtime, spec.args, spec.kwargs)
        if spec.streaming:
            _execute_streaming(runtime, spec, args, kwargs)  # records terminal
            return
        result = spec.func(*args, **kwargs)
    except errors.RayTpuError as e:
        # dependency failed or task-level framework error: propagate as-is
        for rid in spec.return_ids:
            runtime.object_store.put_error(rid, e)
        runtime.on_task_finished(spec)
        runtime.task_events.record(
            spec.task_id, spec.describe(), TaskState.FAILED, error=str(e)
        )
        return
    except BaseException as e:  # noqa: BLE001 - user exception
        if spec.options.retry_exceptions and spec.attempt < spec.options.max_retries:
            spec.attempt += 1
            runtime.scheduler.submit(spec)
            return
        err = errors.TaskError(e, traceback.format_exc(), spec.describe())
        for rid in spec.return_ids:
            runtime.object_store.put_error(rid, err)
        runtime.on_task_finished(spec)
        runtime.task_events.record(
            spec.task_id, spec.describe(), TaskState.FAILED, error=repr(e)
        )
        return
    _store_results(runtime, spec, result)
    runtime.task_events.record(spec.task_id, spec.describe(), TaskState.FINISHED)
    runtime.on_task_finished(spec)


def _execute_streaming(
    runtime: "Runtime", spec: TaskSpec, args, kwargs, fn=None
) -> None:
    """Drive a generator task, publishing each yield as an object. `fn`
    overrides spec.func (actor methods pass the bound method)."""
    from ray_tpu.core.events import TaskState

    gen = runtime.streaming_generators.get(spec.task_id)
    failure: Optional[str] = None
    try:
        it = (fn or spec.func)(*args, **kwargs)
        for i, item in enumerate(it):
            obj_id = ObjectID.for_task_return(spec.task_id, i + 1)
            runtime.object_store.put(obj_id, item)
            if gen is not None:
                gen._append(ObjectRef(obj_id, runtime, spec.describe()))
    except BaseException as e:  # noqa: BLE001
        failure = repr(e)
        err = errors.TaskError(e, traceback.format_exc(), spec.describe())
        if gen is not None:
            obj_id = ObjectID.for_task_return(spec.task_id, 0)
            runtime.object_store.put_error(obj_id, err)
            gen._append(ObjectRef(obj_id, runtime, spec.describe()))
    finally:
        if gen is not None:
            gen._finish()
        runtime.streaming_generators.pop(spec.task_id, None)
        runtime.on_task_finished(spec)
        runtime.task_events.record(
            spec.task_id, spec.describe(),
            TaskState.FAILED if failure else TaskState.FINISHED,
            kind="actor_task" if spec.actor_id is not None else "task",
            actor_id=spec.actor_id, error=failure,
        )


def _store_results(runtime: "Runtime", spec: TaskSpec, result) -> None:
    n = spec.options.num_returns
    if n == 1:
        runtime.object_store.put(spec.return_ids[0], result)
    else:
        if not isinstance(result, (tuple, list)) or len(result) != n:
            err = errors.TaskError(
                ValueError(
                    f"task declared num_returns={n} but returned "
                    f"{type(result).__name__} of length "
                    f"{len(result) if isinstance(result, (tuple, list)) else 'n/a'}"
                ),
                "",
                spec.describe(),
            )
            for rid in spec.return_ids:
                runtime.object_store.put_error(rid, err)
            return
        for rid, val in zip(spec.return_ids, result):
            runtime.object_store.put(rid, val)
