"""Per-process runtime: the CoreWorker equivalent.

Analog of the reference CoreWorker (src/ray/core_worker/core_worker.h:165
— "root class of the worker process, language-independent
functionalities"): owns the object store handle, task submission,
ownership/ref-counting, and the scheduler connection. Single-host today;
the cluster transport (ray_tpu.core.cluster) attaches remote nodes to the
same Gcs + scheduler seam.
"""

from __future__ import annotations

import atexit
import os
import threading
import time
from typing import Any, Iterable, Optional

from ray_tpu.core import errors
from ray_tpu.core.gcs import Gcs, NodeInfo
from ray_tpu.core.object_store import ObjectStore
from ray_tpu.core.ref import ObjectRef, ObjectRefGenerator
from ray_tpu.core.resources import NodeResources, ResourceSet
from ray_tpu.core.scheduler import LocalScheduler
from ray_tpu.core.task import TaskOptions, TaskSpec
from ray_tpu.utils import config
from ray_tpu.utils.ids import NodeID, ObjectID, TaskID, WorkerID
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.runtime")

_runtime_lock = threading.Lock()
_runtime: Optional["Runtime"] = None


class Runtime:
    def __init__(
        self,
        num_cpus: Optional[float] = None,
        num_tpus: Optional[float] = None,
        resources: Optional[dict] = None,
        worker_mode: Optional[str] = None,
        namespace: str = "default",
    ):
        if num_cpus is None:
            # RAY_TPU_NUM_CPUS overrides the physical core count: local
            # actors are THREADS, so the CPU resource is a logical
            # concurrency budget — a 1-core CI box must still run a
            # world_size=2 gang (tests/conftest.py sets a floor of 8)
            env_cpus = os.environ.get("RAY_TPU_NUM_CPUS")
            num_cpus = float(env_cpus) if env_cpus else float(os.cpu_count() or 1)
        if num_tpus is None:
            num_tpus = _detect_tpu_chips()
        total = dict(resources or {})
        total["CPU"] = num_cpus
        if num_tpus:
            total["TPU"] = num_tpus
        total.setdefault("memory", 8 * 1024**3)

        self.namespace = namespace
        self.worker_mode = worker_mode or config.get("worker_mode")
        self.node_id = NodeID.from_random()
        self.worker_id = WorkerID.from_random()
        self.object_store = ObjectStore()
        self.gcs = Gcs()
        self.node_resources = NodeResources(ResourceSet(total))
        self.gcs.register_node(NodeInfo(self.node_id, self.node_resources))
        from ray_tpu.core.events import TaskEventBuffer

        self.scheduler = LocalScheduler(self, self.node_resources)
        self.task_events = TaskEventBuffer()
        self.streaming_generators: dict[TaskID, ObjectRefGenerator] = {}
        self._put_counter = 0
        self._task_counter = 0
        self._lock = threading.Lock()
        self._pending_tasks: set[TaskID] = set()
        self._process_pool = None

    # -- lazily built process pool ------------------------------------------

    @property
    def process_pool(self):
        if self._process_pool is None:
            from ray_tpu.core.process_pool import ProcessPool

            self._process_pool = ProcessPool()
        return self._process_pool

    # -- object API ----------------------------------------------------------

    def put(self, value: Any) -> ObjectRef:
        with self._lock:
            self._put_counter += 1
            idx = self._put_counter
        obj_id = ObjectID.for_put(TaskID(self.worker_id.binary()), idx)
        self.object_store.put(obj_id, value)
        return ObjectRef(obj_id, self, "put")

    def get(self, refs: list[ObjectRef], timeout: Optional[float] = None) -> list[Any]:
        deadline = None if timeout is None else time.monotonic() + timeout
        out = []
        for ref in refs:
            remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
            try:
                out.append(self.object_store.get(ref.id, remaining))
            except errors.GetTimeoutError:
                raise errors.GetTimeoutError(
                    f"get() timed out after {timeout}s waiting for {ref}"
                ) from None
        return out

    def wait(
        self,
        refs: list[ObjectRef],
        num_returns: int = 1,
        timeout: Optional[float] = None,
    ) -> tuple[list[ObjectRef], list[ObjectRef]]:
        if num_returns > len(refs):
            raise ValueError("num_returns exceeds number of refs")
        if len({r.id for r in refs}) != len(refs):
            raise ValueError("wait() got duplicate ObjectRefs")
        cv = threading.Condition()
        ready_ids: set[ObjectID] = set()

        def on_ready(obj_id: ObjectID) -> None:
            with cv:
                ready_ids.add(obj_id)
                cv.notify_all()

        for ref in refs:
            self.object_store.wait_async(ref.id, on_ready)
        deadline = None if timeout is None else time.monotonic() + timeout
        try:
            with cv:
                while len(ready_ids) < num_returns:
                    remaining = None if deadline is None else deadline - time.monotonic()
                    if remaining is not None and remaining <= 0:
                        break
                    cv.wait(remaining if remaining is not None else 0.5)
                # at most num_returns in the ready list (reference ray.wait
                # contract, python/ray/_private/worker.py:2878)
                ready = [r for r in refs if r.id in ready_ids][:num_returns]
                ready_set = {r.id for r in ready}
                not_ready = [r for r in refs if r.id not in ready_set]
            return ready, not_ready
        finally:
            # deregister unfired callbacks (polling wait() must not leak)
            for ref in refs:
                self.object_store.cancel_wait(ref.id, on_ready)

    # -- task submission -----------------------------------------------------

    def submit_task(
        self,
        func,
        args: tuple,
        kwargs: dict,
        options: TaskOptions,
    ) -> list[ObjectRef] | ObjectRefGenerator:
        task_id = TaskID.from_random()
        streaming = options.num_returns == "streaming"
        n = 1 if streaming else int(options.num_returns)
        from ray_tpu.obs import context as trace_context

        ctx = trace_context.current()
        spec = TaskSpec(
            task_id=task_id,
            func=func,
            args=args,
            kwargs=kwargs,
            options=options,
            return_ids=[ObjectID.for_task_return(task_id, i) for i in range(n)],
            streaming=streaming,
            trace=ctx.to_dict() if ctx is not None else None,
        )
        self._retain_arg_refs(spec)
        with self._lock:
            self._pending_tasks.add(task_id)
        from ray_tpu.core.events import TaskState

        self.task_events.record(
            task_id, spec.describe(), TaskState.SUBMITTED
        )
        if streaming:
            gen = ObjectRefGenerator(self, spec.describe())
            self.streaming_generators[task_id] = gen
            self.scheduler.submit(spec)
            return gen
        refs = [ObjectRef(rid, self, spec.describe()) for rid in spec.return_ids]
        self.scheduler.submit(spec)
        return refs

    def _retain_arg_refs(self, spec: TaskSpec) -> None:
        # Hold arg objects alive while the task is in flight (the reference
        # tracks this as task dependencies in ReferenceCounter).
        for a in list(spec.args) + list(spec.kwargs.values()):
            if isinstance(a, ObjectRef):
                self.object_store.add_ref(a.id)

    def on_task_finished(self, spec: TaskSpec) -> None:
        for a in list(spec.args) + list(spec.kwargs.values()):
            if isinstance(a, ObjectRef):
                self.object_store.remove_ref(a.id)
        with self._lock:
            self._pending_tasks.discard(spec.task_id)

    def pending_task_count(self) -> int:
        with self._lock:
            return len(self._pending_tasks)

    # -- ref counting hooks --------------------------------------------------

    def on_ref_serialized(self, obj_id: ObjectID) -> None:
        self.object_store.add_ref(obj_id)

    def on_ref_deleted(self, obj_id: ObjectID) -> None:
        self.object_store.remove_ref(obj_id)

    # -- shutdown ------------------------------------------------------------

    def shutdown(self) -> None:
        self.scheduler.shutdown()
        if self._process_pool is not None:
            self._process_pool.shutdown()


def _detect_tpu_chips() -> float:
    """Count local TPU chips without initializing a backend (env-driven,
    mirroring the detection ladder of the reference's TPUAcceleratorManager,
    python/ray/_private/accelerators/tpu.py:14-68)."""
    env = os.environ.get("TPU_VISIBLE_CHIPS") or os.environ.get("TPU_CHIPS")
    if env:
        return float(len([c for c in env.split(",") if c.strip()]))
    # Explicit opt-in count (set by tests / launchers); never probe hardware
    # here — backend init is expensive and may not be safe at import time.
    return float(os.environ.get("RAY_TPU_NUM_CHIPS", 0) or 0)


def get_runtime() -> Runtime:
    global _runtime
    with _runtime_lock:
        if _runtime is None:
            _runtime = Runtime()
            atexit.register(lambda: _runtime and _runtime.shutdown())
        return _runtime


def init_runtime(**kwargs) -> Runtime:
    global _runtime
    with _runtime_lock:
        if _runtime is not None:
            raise RuntimeError("ray_tpu already initialized; call shutdown() first")
        _runtime = Runtime(**kwargs)
        return _runtime


def shutdown_runtime() -> None:
    global _runtime
    with _runtime_lock:
        if _runtime is not None:
            _runtime.shutdown()
            _runtime = None


def is_initialized() -> bool:
    with _runtime_lock:
        return _runtime is not None
