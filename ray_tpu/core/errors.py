"""User-visible error types (analog of reference python/ray/exceptions.py)."""

from __future__ import annotations


class RayTpuError(Exception):
    pass


class TaskError(RayTpuError):
    """A task raised an exception; carries the formatted remote traceback."""

    def __init__(self, cause: BaseException, remote_tb: str, task_desc: str = ""):
        self.cause = cause
        self.remote_tb = remote_tb
        self.task_desc = task_desc
        super().__init__(f"task {task_desc} failed: {cause!r}\n--- remote traceback ---\n{remote_tb}")


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died (e.g. OOM-killed)."""


class ActorDiedError(RayTpuError):
    """Method called on an actor that is dead (ctor failed, killed, or crashed
    past its restart budget)."""


class ActorUnavailableError(RayTpuError):
    """Actor temporarily unavailable (restarting)."""


class ObjectLostError(RayTpuError):
    pass


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class TaskCancelledError(RayTpuError):
    pass


class PlacementGroupUnavailableError(RayTpuError):
    pass
