"""TPU accelerator management: chips and pod slices as schedulable resources.

Analog of the reference's TPUAcceleratorManager
(python/ray/_private/accelerators/tpu.py:70): detection via environment
(GKE-style vars; no metadata-server probe here — zero-egress safe),
`TPU_VISIBLE_CHIPS` isolation (tpu.py:154), valid per-host chip counts
{1,2,4,8} (tpu.py:14,140-148), and the pod-slice resource pattern
(tpu.py:330-393): every worker of a slice advertises `{slice_name}: 1`
and worker 0 additionally `TPU-{pod_type}-head: 1`, which is the gang-
scheduling hook `slice_run` builds on.
"""

from __future__ import annotations

import dataclasses
import os
import re
from typing import Optional

VALID_CHIPS_PER_HOST = (1, 2, 4, 8)

# chips per host and whether the pod-type number counts cores (2/chip) or chips
_GENERATIONS = {
    "v2": {"chips_per_host": 4, "number_is_cores": True},
    "v3": {"chips_per_host": 4, "number_is_cores": True},
    "v4": {"chips_per_host": 4, "number_is_cores": True},
    "v5p": {"chips_per_host": 4, "number_is_cores": True},
    "v5litepod": {"chips_per_host": 8, "number_is_cores": False},
    "v5e": {"chips_per_host": 8, "number_is_cores": False},
    "v6e": {"chips_per_host": 8, "number_is_cores": False},
}


@dataclasses.dataclass(frozen=True)
class TpuTopology:
    pod_type: str  # e.g. "v5p-16", "v5e-64"
    generation: str
    num_chips: int
    chips_per_host: int

    @property
    def num_hosts(self) -> int:
        return max(1, self.num_chips // self.chips_per_host)

    @property
    def slice_resource_name(self) -> str:
        return f"TPU-{self.pod_type}"

    @property
    def head_resource_name(self) -> str:
        return f"TPU-{self.pod_type}-head"


def parse_pod_type(pod_type: str) -> TpuTopology:
    m = re.fullmatch(r"(v\d+[a-z]*(?:pod)?)-(\d+)", pod_type)
    if not m:
        raise ValueError(f"unparseable TPU pod type {pod_type!r} (want e.g. 'v5p-16')")
    gen, number = m.group(1), int(m.group(2))
    info = _GENERATIONS.get(gen)
    if info is None:
        raise ValueError(f"unknown TPU generation {gen!r} in {pod_type!r}")
    num_chips = number // 2 if info["number_is_cores"] else number
    chips_per_host = min(info["chips_per_host"], max(1, num_chips))
    return TpuTopology(pod_type, gen, num_chips, chips_per_host)


class TpuAcceleratorManager:
    """Per-node TPU detection + isolation (env-driven)."""

    @staticmethod
    def detect_num_chips() -> int:
        visible = os.environ.get("TPU_VISIBLE_CHIPS")
        if visible:
            return len([c for c in visible.split(",") if c.strip()])
        chips = os.environ.get("TPU_CHIPS_PER_HOST_BOUNDS")  # e.g. "2,2,1"
        if chips:
            n = 1
            for part in chips.split(","):
                n *= int(part)
            return n
        explicit = os.environ.get("RAY_TPU_NUM_CHIPS")
        if explicit:
            return int(explicit)
        return 0

    @staticmethod
    def detect_pod_type() -> Optional[str]:
        for var in ("TPU_ACCELERATOR_TYPE", "TPU_POD_TYPE"):
            val = os.environ.get(var)
            if val:
                return val
        return None

    @staticmethod
    def detect_worker_id() -> int:
        for var in ("TPU_WORKER_ID", "CLOUD_TPU_TASK_ID"):
            val = os.environ.get(var)
            if val is not None:
                return int(val)
        return 0

    @staticmethod
    def set_visible_chips(chip_ids: list[int]) -> None:
        """Isolate a worker to specific chips (reference tpu.py:154)."""
        if len(chip_ids) not in VALID_CHIPS_PER_HOST:
            raise ValueError(
                f"TPU workers may own {VALID_CHIPS_PER_HOST} chips, not {len(chip_ids)}"
            )
        os.environ["TPU_VISIBLE_CHIPS"] = ",".join(str(c) for c in chip_ids)

    @classmethod
    def node_resources(cls) -> dict:
        """Resources this node should advertise (chips + slice membership)."""
        out: dict = {}
        chips = cls.detect_num_chips()
        if chips:
            out["TPU"] = float(chips)
        pod_type = cls.detect_pod_type()
        if pod_type:
            topo = parse_pod_type(pod_type)
            out[topo.slice_resource_name] = 1.0
            if cls.detect_worker_id() == 0:
                out[topo.head_resource_name] = 1.0
        return out


def slice_placement_group(pod_type: str, name: str = "", strict: Optional[bool] = None):
    """Reserve one bundle per host of a slice (STRICT_SPREAD over the pod's
    hosts; each bundle pins the host's chips + slice membership).

    strict=None auto-relaxes to SPREAD when the cluster has a single node
    (dev-box simulation of a slice); real multi-host clusters keep the
    one-bundle-per-host guarantee.
    """
    from ray_tpu.core import api, runtime as rt

    topo = parse_pod_type(pod_type)
    bundles = [
        {"TPU": float(topo.chips_per_host), topo.slice_resource_name: 1.0}
        for _ in range(topo.num_hosts)
    ]
    if topo.num_hosts == 1:
        strategy = "STRICT_PACK"
    elif strict is None:
        multi = len(rt.get_runtime().gcs.alive_nodes()) > 1
        strategy = "STRICT_SPREAD" if multi else "SPREAD"
    else:
        strategy = "STRICT_SPREAD" if strict else "SPREAD"
    return api.placement_group(bundles, strategy=strategy, name=name or f"slice-{pod_type}")


def slice_run(fn, pod_type: str, *args, pg=None, **kwargs):
    """Gang-launch `fn(rank, world_size, *args)` on every host of a slice.

    The one-liner version of the reference's documented SPMD pattern
    (tpu.py:356-365: schedule a task per host via the pod-slice resources).
    Returns the list of per-host ObjectRefs (rank order).
    """
    from ray_tpu.core import api

    topo = parse_pod_type(pod_type)
    own_pg = pg is None
    if own_pg:
        pg = slice_placement_group(pod_type)
        if not pg.ready(timeout=60):
            raise TimeoutError(f"slice placement group for {pod_type} not ready")
    remote_fn = api.remote(fn) if not isinstance(fn, api.RemoteFunction) else fn
    refs = []
    for rank in range(topo.num_hosts):
        strategy = api.PlacementGroupSchedulingStrategy(pg, rank)
        refs.append(
            remote_fn.options(
                num_cpus=0,
                num_tpus=float(topo.chips_per_host),
                scheduling_strategy=strategy,
            ).remote(rank, topo.num_hosts, *args, **kwargs)
        )
    return refs
