"""Task specification (analog of reference TaskSpecification,
src/ray/common/task/task_spec.h, much slimmed: no protobuf on the
single-host fast path; specs cross process/node boundaries as msgpack/
cloudpickle only when they must)."""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

from ray_tpu.core.resources import ResourceSet
from ray_tpu.utils.ids import ActorID, ObjectID, PlacementGroupID, TaskID


@dataclasses.dataclass
class TaskOptions:
    num_cpus: float = 1.0
    num_tpus: float = 0.0
    resources: dict = dataclasses.field(default_factory=dict)
    num_returns: int | str = 1  # int or "streaming"
    max_retries: int = 3
    retry_exceptions: bool = False
    name: Optional[str] = None
    placement_group: Optional[Any] = None  # PlacementGroup
    placement_group_bundle_index: int = -1
    scheduling_strategy: Optional[Any] = None
    # {env_vars, working_dir, py_modules} — cluster mode only (worker
    # processes); the in-process thread runtime cannot isolate an env
    runtime_env: Optional[dict] = None

    def resource_set(self) -> ResourceSet:
        req = dict(self.resources)
        if self.num_cpus:
            req["CPU"] = req.get("CPU", 0) + self.num_cpus
        if self.num_tpus:
            req["TPU"] = req.get("TPU", 0) + self.num_tpus
        return ResourceSet(req)


@dataclasses.dataclass
class ActorOptions:
    num_cpus: float = 1.0
    num_tpus: float = 0.0
    resources: dict = dataclasses.field(default_factory=dict)
    name: Optional[str] = None
    get_if_exists: bool = False
    max_restarts: int = 0
    max_task_retries: int = 0
    max_concurrency: int = 1
    lifetime: Optional[str] = None  # None | "detached"
    placement_group: Optional[Any] = None
    placement_group_bundle_index: int = -1
    scheduling_strategy: Optional[Any] = None
    runtime_env: Optional[dict] = None

    def resource_set(self) -> ResourceSet:
        req = dict(self.resources)
        if self.num_cpus:
            req["CPU"] = req.get("CPU", 0) + self.num_cpus
        if self.num_tpus:
            req["TPU"] = req.get("TPU", 0) + self.num_tpus
        return ResourceSet(req)


@dataclasses.dataclass
class TaskSpec:
    task_id: TaskID
    func: Callable  # already bound/unpickled in-process
    args: tuple
    kwargs: dict
    options: TaskOptions
    return_ids: list[ObjectID] = dataclasses.field(default_factory=list)
    # actor tasks
    actor_id: Optional[ActorID] = None
    method_name: Optional[str] = None
    # bookkeeping
    attempt: int = 0
    streaming: bool = False
    # submitter's TraceContext as a dict (ray_tpu.obs.context): attached
    # around execution so task events + nested calls carry the trace
    trace: Optional[dict] = None

    def describe(self) -> str:
        # cached: called on every event record / error message
        d = getattr(self, "_describe", None)
        if d is None:
            name = self.options.name or getattr(self.func, "__name__", "task")
            if self.method_name:
                name = f"{name}.{self.method_name}"
            d = f"{name}[{self.task_id.hex()[:8]}]"
            object.__setattr__(self, "_describe", d)
        return d
