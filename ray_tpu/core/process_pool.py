"""Process worker pool: crash-isolated task execution.

Analog of the reference WorkerPool (src/ray/raylet/worker_pool.h:125):
persistent worker processes leased per task, cached between tasks. Used
only for `worker_mode="process"` tasks — the TPU-idiomatic default is
thread execution inside the host's single JAX process (see scheduler.py).
Worker death surfaces as WorkerCrashedError so the scheduler can retry
(the reference's max_retries path, src/ray/core_worker/task_manager.h:260).
"""

from __future__ import annotations

import multiprocessing as mp
import os
import pickle
import threading
import traceback
from typing import TYPE_CHECKING, Optional

import cloudpickle

from ray_tpu.chaos import harness as _chaos
from ray_tpu.core import errors
from ray_tpu.core.task import TaskSpec
from ray_tpu.utils.logging import get_logger

if TYPE_CHECKING:
    from ray_tpu.core.runtime import Runtime

logger = get_logger("ray_tpu.process_pool")

# forkserver: children fork from a clean helper process that has never
# imported JAX, so forking after the driver initialized a TPU backend
# cannot deadlock in a cloned runtime thread (plain "fork" prints JAX's
# fork-hazard warning and can hang on TPU hosts)
_CTX = mp.get_context("forkserver")

# Buffers above this ride the C++ shared-memory store (zero-copy mmap views
# in the peer process) instead of being copied through the pipe. The store
# is the plasma-equivalent (ray_tpu/native/src/shm_store.cc).
_SHM_THRESHOLD = 32 * 1024
_shm_counter = threading.Lock(), [0]


def _next_shm_id(prefix: int) -> bytes:
    lock, counter = _shm_counter
    with lock:
        counter[0] += 1
        n = counter[0]
    return prefix.to_bytes(4, "little") + os.getpid().to_bytes(4, "little") + n.to_bytes(8, "little")


class _BufferChannel:
    """Pickle-5 out-of-band buffer transport: big buffers via the shm
    store, small ones inline. Symmetric for both directions."""

    def __init__(self, store):
        self.store = store  # ShmObjectStore or None (inline-only fallback)

    def encode(self, buffers: list) -> tuple[list, list[bytes]]:
        """Returns (meta list, shm ids to delete after the peer is done)."""
        meta, owned = [], []
        for b in buffers:
            try:
                raw = b.raw() if hasattr(b, "raw") else memoryview(b)
            except BufferError:  # non-contiguous pickle buffer
                meta.append(("inline", bytes(b)))
                continue
            if self.store is not None and raw.nbytes >= _SHM_THRESHOLD:
                oid = _next_shm_id(0xB0F)
                try:
                    # keep the producer ref until the peer is done: put()
                    # would release it and expose the buffer to eviction
                    # before the peer's get(). Single copy: source view ->
                    # mapping, no intermediate bytes materialization.
                    buf, _ = self.store.create_buffer(oid, raw.nbytes)
                    memoryview(buf).cast("B")[:] = raw.cast("B")
                    self.store.seal(oid)
                    meta.append(("shm", oid, raw.nbytes))
                    owned.append(oid)
                    continue
                except MemoryError:
                    pass  # store full: fall through to inline
            meta.append(("inline", raw.tobytes()))
        return meta, owned

    def decode(self, meta: list) -> tuple[list, list[bytes]]:
        """Returns (buffer views, shm ids to release after use)."""
        views, held = [], []
        for m in meta:
            if m[0] == "shm":
                view = self.store.get(m[1])
                if view is None:
                    raise errors.ObjectLostError(
                        f"shm buffer {m[1]!r} missing (evicted?)"
                    )
                views.append(view[: m[2]])
                held.append(m[1])
            else:
                views.append(memoryview(m[1]))
        return views, held

    def release(self, ids: list[bytes]) -> None:
        for oid in ids:
            try:
                self.store.release(oid)
            except Exception:
                pass

    def delete(self, ids: list[bytes]) -> None:
        for oid in ids:
            try:
                self.store.delete(oid)
            except Exception:
                pass

    def producer_done(self, ids: list[bytes]) -> None:
        """Drop the encode()-held refs and free the objects."""
        self.release(ids)
        self.delete(ids)

    def consumer_done_and_free(self, ids: list[bytes]) -> None:
        """Consumer drops its get() ref AND the remote producer's encode
        ref (the producer moved on — cross-process handoff), then frees."""
        self.release(ids)
        self.release(ids)
        self.delete(ids)

    def reclaim_dead_peer(self, ids: list[bytes]) -> None:
        """A peer died holding refs (crash mid-task): refcounts are stuck,
        so reclaim unconditionally or the capacity leaks forever."""
        if self.store is None:
            return
        for oid in ids:
            try:
                self.store.force_delete(oid)
            except Exception:
                pass


class _ValueUnpickler(pickle.Unpickler):
    """Child side: persistent ids carry already-resolved object values."""

    def persistent_load(self, pid):
        kind, value = pid
        if kind == "resolved":
            return value
        raise pickle.UnpicklingError(f"unknown persistent id {kind!r}")


def _loads_with_values_buffers(data: bytes, buffers: list):
    import io

    return _ValueUnpickler(io.BytesIO(data), buffers=buffers or None).load()


def _dumps_resolving_refs(obj, runtime) -> tuple[bytes, list]:
    """Parent side: replace ObjectRefs nested anywhere in the args with
    their resolved values (the child has its own empty runtime — a pickled
    ref would rebuild against the wrong store and hang forever).
    Returns (payload, out-of-band pickle-5 buffers)."""
    import io

    from ray_tpu.core.ref import ObjectRef

    buf = io.BytesIO()
    buffers: list = []

    class _P(cloudpickle.CloudPickler):
        def persistent_id(self, o):
            if isinstance(o, ObjectRef):
                return ("resolved", runtime.object_store.get(o.id))
            # ActorHandles cannot cross the process boundary (the actor
            # lives in the host process); fail loudly, not with a hang.
            from ray_tpu.core.api import ActorHandle

            if isinstance(o, ActorHandle):
                raise TypeError(
                    "ActorHandle cannot be passed to a process-mode task: "
                    "actors live in the host process (use worker_mode='thread' "
                    "tasks to interact with actors)"
                )
            return None

    _P(buf, protocol=5, buffer_callback=buffers.append).dump(obj)
    return buf.getvalue(), buffers


def _worker_main(conn, shm_path: Optional[str]) -> None:
    channel = None

    def get_channel():
        nonlocal channel
        if channel is None:
            store = None
            if shm_path is not None and os.path.exists(shm_path):
                from ray_tpu.native.shm import ShmObjectStore

                store = ShmObjectStore.open(shm_path)
            channel = _BufferChannel(store)
        return channel

    while True:
        try:
            msg = conn.recv_bytes()
        except (EOFError, OSError):
            return
        held: list = []
        try:
            envelope, meta = pickle.loads(msg)
            if meta:
                views, held = get_channel().decode(meta)
            else:
                views = []
            func, args, kwargs = _loads_with_values_buffers(envelope, views)
            result = func(*args, **kwargs)
            out_buffers: list = []
            out_payload = cloudpickle.dumps(
                ("ok", result), protocol=5, buffer_callback=out_buffers.append
            )
            out_meta, _owned = (
                get_channel().encode(out_buffers) if out_buffers else ([], [])
            )
            payload = pickle.dumps((out_payload, out_meta))
        except BaseException as e:  # noqa: BLE001
            payload = pickle.dumps(
                (cloudpickle.dumps(("err", (e, traceback.format_exc()))), [])
            )
        finally:
            if held and channel is not None:
                channel.release(held)
        try:
            conn.send_bytes(payload)
        except (BrokenPipeError, OSError):
            return


class _Worker:
    def __init__(self, shm_path: Optional[str] = None):
        self.parent_conn, child_conn = _CTX.Pipe()
        self.proc = _CTX.Process(
            target=_worker_main, args=(child_conn, shm_path), daemon=True
        )
        self.proc.start()
        child_conn.close()

    @property
    def pid(self) -> int:
        return self.proc.pid

    def alive(self) -> bool:
        return self.proc.is_alive()

    def kill(self) -> None:
        try:
            self.proc.kill()
        except Exception:
            pass


class ProcessPool:
    def __init__(self, max_workers: int = 8, shm_capacity: int = 256 << 20):
        self._free: list[_Worker] = []
        self._lock = threading.Lock()
        self._max = max_workers
        self._count = 0
        self._running: dict[bytes, _Worker] = {}  # task_id bytes -> worker
        self._shm_capacity = shm_capacity
        self._shm_path = f"/dev/shm/ray_tpu_store_{os.getpid()}.shm"
        self._channel: Optional[_BufferChannel] = None
        # eager: workers fork knowing whether the store exists (tmpfs files
        # are sparse, so unused capacity costs nothing)
        self._get_channel()

    def _get_channel(self) -> _BufferChannel:
        """Lazily create the shared store (plasma-equivalent); fall back to
        inline pipe transport if the native lib can't build."""
        with self._lock:
            if self._channel is None:
                store = None
                try:
                    from ray_tpu.native.shm import ShmObjectStore

                    store = ShmObjectStore.create(
                        self._shm_path, self._shm_capacity
                    )
                except Exception:
                    logger.warning(
                        "native shm store unavailable; using inline transport",
                        exc_info=True,
                    )
                    self._shm_path = None
                self._channel = _BufferChannel(store)
            return self._channel

    def run(self, spec: TaskSpec):
        """Execute the task in a leased worker; blocks until done."""
        from ray_tpu.core.runtime import get_runtime
        from ray_tpu.core.scheduler import resolve_args

        runtime = get_runtime()
        args, kwargs = resolve_args(runtime, spec.args, spec.kwargs)
        envelope, out_buffers = _dumps_resolving_refs(
            (spec.func, args, kwargs), runtime
        )
        channel = self._get_channel()
        arg_meta, owned = channel.encode(out_buffers) if out_buffers else ([], [])
        payload_out = pickle.dumps((envelope, arg_meta))
        worker = self._lease()
        tid = spec.task_id.binary()
        self._running[tid] = worker
        if _chaos.ACTIVE is not None:
            for _f in _chaos.fire("process_pool.task",
                                  kinds=(_chaos.KILL_WORKER,),
                                  desc=spec.describe()):
                if _f.kind == _chaos.KILL_WORKER:
                    # worker dies out from under the task: the pipe EOF
                    # below surfaces as WorkerCrashedError and the
                    # scheduler's max_retries path re-runs the task
                    worker.kill()
        try:
            try:
                try:
                    worker.parent_conn.send_bytes(payload_out)
                    payload = worker.parent_conn.recv_bytes()
                except (EOFError, BrokenPipeError, OSError):
                    # the dead worker may hold refs on the arg objects:
                    # normal delete would fail, leaking store capacity
                    if owned:
                        channel.reclaim_dead_peer(owned)
                        owned = []
                    raise errors.WorkerCrashedError(
                        f"worker pid={worker.pid} died executing {spec.describe()}"
                    ) from None
            finally:
                if owned and channel.store is not None:
                    channel.producer_done(owned)
            result_payload, result_meta = pickle.loads(payload)
            held: list = []
            views = []
            if result_meta:
                raw_views, held = channel.decode(result_meta)
                # own the data BEFORE unpickling: reconstructed objects of
                # ANY container shape then never alias soon-freed shm pages
                views = [bytearray(v) for v in raw_views]
                del raw_views
                channel.consumer_done_and_free(held)
                held = []
            status, value = pickle.loads(result_payload, buffers=views or None)
            if status == "err":
                exc, tb = value
                raise errors.TaskError(exc, tb, spec.describe())
            self._release(worker)
            return value
        except errors.WorkerCrashedError:
            # never re-pool after a crash signal, even if is_alive() races
            self._discard(worker)
            raise
        except errors.RayTpuError:
            if not worker.alive():
                self._discard(worker)
            else:
                self._release(worker)
            raise
        except BaseException:
            # e.g. parent-side unpickling failure: never abandon the lease
            self._discard(worker)
            raise
        finally:
            self._running.pop(tid, None)

    def kill_worker_for(self, task_id_bytes: bytes) -> bool:
        """Fault injection: kill the worker running the given task."""
        worker = self._running.get(task_id_bytes)
        if worker is None:
            return False
        worker.kill()
        return True

    def _lease(self) -> _Worker:
        with self._lock:
            while self._free:
                w = self._free.pop()
                if w.alive():
                    return w
                self._discard_locked(w)
            self._count += 1
            return _Worker(self._shm_path)

    def _release(self, worker: _Worker) -> None:
        with self._lock:
            if worker.alive() and len(self._free) < self._max:
                self._free.append(worker)
            else:
                self._discard_locked(worker)

    def _discard(self, worker: _Worker) -> None:
        with self._lock:
            self._discard_locked(worker)

    def _discard_locked(self, worker: _Worker) -> None:
        self._count -= 1
        worker.kill()

    def shutdown(self) -> None:
        # kill running workers first so blocked run() calls fail fast via
        # the crash path, then close the store (its Python guard turns any
        # straggler access into OSError instead of a native SIGSEGV)
        for w in list(self._running.values()):
            w.kill()
        with self._lock:
            for w in self._free:
                w.kill()
            self._free.clear()
            if self._channel is not None and self._channel.store is not None:
                self._channel.store.close()
                self._channel = None
