"""Process worker pool: crash-isolated task execution.

Analog of the reference WorkerPool (src/ray/raylet/worker_pool.h:125):
persistent worker processes leased per task, cached between tasks. Used
only for `worker_mode="process"` tasks — the TPU-idiomatic default is
thread execution inside the host's single JAX process (see scheduler.py).
Worker death surfaces as WorkerCrashedError so the scheduler can retry
(the reference's max_retries path, src/ray/core_worker/task_manager.h:260).
"""

from __future__ import annotations

import multiprocessing as mp
import os
import pickle
import threading
import traceback
from typing import TYPE_CHECKING, Optional

import cloudpickle

from ray_tpu.core import errors
from ray_tpu.core.task import TaskSpec
from ray_tpu.utils.logging import get_logger

if TYPE_CHECKING:
    from ray_tpu.core.runtime import Runtime

logger = get_logger("ray_tpu.process_pool")

_CTX = mp.get_context("fork")  # cheap startup; workers never touch the TPU


class _ValueUnpickler(pickle.Unpickler):
    """Child side: persistent ids carry already-resolved object values."""

    def persistent_load(self, pid):
        kind, value = pid
        if kind == "resolved":
            return value
        raise pickle.UnpicklingError(f"unknown persistent id {kind!r}")


def _loads_with_values(data: bytes):
    import io

    return _ValueUnpickler(io.BytesIO(data)).load()


def _dumps_resolving_refs(obj, runtime) -> bytes:
    """Parent side: replace ObjectRefs nested anywhere in the args with
    their resolved values (the child has its own empty runtime — a pickled
    ref would rebuild against the wrong store and hang forever)."""
    import io

    from ray_tpu.core.ref import ObjectRef

    buf = io.BytesIO()

    class _P(cloudpickle.CloudPickler):
        def persistent_id(self, o):
            if isinstance(o, ObjectRef):
                return ("resolved", runtime.object_store.get(o.id))
            # ActorHandles cannot cross the process boundary (the actor
            # lives in the host process); fail loudly, not with a hang.
            from ray_tpu.core.api import ActorHandle

            if isinstance(o, ActorHandle):
                raise TypeError(
                    "ActorHandle cannot be passed to a process-mode task: "
                    "actors live in the host process (use worker_mode='thread' "
                    "tasks to interact with actors)"
                )
            return None

    _P(buf, protocol=5).dump(obj)
    return buf.getvalue()


def _worker_main(conn) -> None:
    while True:
        try:
            msg = conn.recv_bytes()
        except (EOFError, OSError):
            return
        try:
            func, args, kwargs = _loads_with_values(msg)
            result = func(*args, **kwargs)
            payload = cloudpickle.dumps(("ok", result))
        except BaseException as e:  # noqa: BLE001
            payload = cloudpickle.dumps(("err", (e, traceback.format_exc())))
        try:
            conn.send_bytes(payload)
        except (BrokenPipeError, OSError):
            return


class _Worker:
    def __init__(self):
        self.parent_conn, child_conn = _CTX.Pipe()
        self.proc = _CTX.Process(target=_worker_main, args=(child_conn,), daemon=True)
        self.proc.start()
        child_conn.close()

    @property
    def pid(self) -> int:
        return self.proc.pid

    def alive(self) -> bool:
        return self.proc.is_alive()

    def kill(self) -> None:
        try:
            self.proc.kill()
        except Exception:
            pass


class ProcessPool:
    def __init__(self, max_workers: int = 8):
        self._free: list[_Worker] = []
        self._lock = threading.Lock()
        self._max = max_workers
        self._count = 0
        self._running: dict[bytes, _Worker] = {}  # task_id bytes -> worker

    def run(self, spec: TaskSpec):
        """Execute the task in a leased worker; blocks until done."""
        from ray_tpu.core.runtime import get_runtime
        from ray_tpu.core.scheduler import resolve_args

        runtime = get_runtime()
        args, kwargs = resolve_args(runtime, spec.args, spec.kwargs)
        payload_out = _dumps_resolving_refs((spec.func, args, kwargs), runtime)
        worker = self._lease()
        tid = spec.task_id.binary()
        self._running[tid] = worker
        try:
            try:
                worker.parent_conn.send_bytes(payload_out)
                payload = worker.parent_conn.recv_bytes()
            except (EOFError, BrokenPipeError, OSError):
                raise errors.WorkerCrashedError(
                    f"worker pid={worker.pid} died executing {spec.describe()}"
                ) from None
            status, value = pickle.loads(payload)
            if status == "err":
                exc, tb = value
                raise errors.TaskError(exc, tb, spec.describe())
            self._release(worker)
            return value
        except errors.WorkerCrashedError:
            # never re-pool after a crash signal, even if is_alive() races
            self._discard(worker)
            raise
        except errors.RayTpuError:
            if not worker.alive():
                self._discard(worker)
            else:
                self._release(worker)
            raise
        except BaseException:
            # e.g. parent-side unpickling failure: never abandon the lease
            self._discard(worker)
            raise
        finally:
            self._running.pop(tid, None)

    def kill_worker_for(self, task_id_bytes: bytes) -> bool:
        """Fault injection: kill the worker running the given task."""
        worker = self._running.get(task_id_bytes)
        if worker is None:
            return False
        worker.kill()
        return True

    def _lease(self) -> _Worker:
        with self._lock:
            while self._free:
                w = self._free.pop()
                if w.alive():
                    return w
                self._discard_locked(w)
            self._count += 1
            return _Worker()

    def _release(self, worker: _Worker) -> None:
        with self._lock:
            if worker.alive() and len(self._free) < self._max:
                self._free.append(worker)
            else:
                self._discard_locked(worker)

    def _discard(self, worker: _Worker) -> None:
        with self._lock:
            self._discard_locked(worker)

    def _discard_locked(self, worker: _Worker) -> None:
        self._count -= 1
        worker.kill()

    def shutdown(self) -> None:
        with self._lock:
            for w in self._free:
                w.kill()
            self._free.clear()
