"""Control plane: cluster metadata registries.

Analog of the reference GCS server (src/ray/gcs/gcs_server/ —
GcsNodeManager, GcsActorManager naming, InternalKVManager
gcs_kv_manager.h). In-process for the single-host runtime; the same
object is served over the node RPC layer for multi-host clusters (see
ray_tpu.core.cluster), which is the GCS-server split of the reference.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Any, Optional

from ray_tpu.core.resources import NodeResources, ResourceSet
from ray_tpu.utils.ids import ActorID, NodeID

if TYPE_CHECKING:
    from ray_tpu.core.actor_runtime import Actor


@dataclass
class NodeInfo:
    node_id: NodeID
    resources: NodeResources
    hostname: str = "localhost"
    alive: bool = True
    last_heartbeat: float = field(default_factory=time.monotonic)
    labels: dict = field(default_factory=dict)


class Gcs:
    def __init__(self):
        self._lock = threading.RLock()
        self._nodes: dict[NodeID, NodeInfo] = {}
        self._named_actors: dict[tuple[str, str], ActorID] = {}  # (ns, name) -> id
        self._actors: dict[ActorID, "Actor"] = {}
        self._placement_groups: dict = {}
        self._kv: dict[str, dict[bytes, bytes]] = {}  # namespace -> k/v

    # -- nodes ---------------------------------------------------------------

    def register_node(self, info: NodeInfo) -> None:
        with self._lock:
            self._nodes[info.node_id] = info

    def remove_node(self, node_id: NodeID) -> None:
        with self._lock:
            info = self._nodes.get(node_id)
            if info:
                info.alive = False

    def heartbeat(self, node_id: NodeID) -> None:
        with self._lock:
            info = self._nodes.get(node_id)
            if info:
                info.last_heartbeat = time.monotonic()

    def get_node(self, node_id: NodeID) -> Optional[NodeInfo]:
        with self._lock:
            return self._nodes.get(node_id)

    def alive_nodes(self) -> list[NodeInfo]:
        with self._lock:
            return [n for n in self._nodes.values() if n.alive]

    def cluster_resources(self) -> dict:
        with self._lock:
            total: dict[str, float] = {}
            for n in self._nodes.values():
                if not n.alive:
                    continue
                for k, v in n.resources.total.items():
                    total[k] = total.get(k, 0.0) + v
            return total

    def available_resources(self) -> dict:
        with self._lock:
            total: dict[str, float] = {}
            for n in self._nodes.values():
                if not n.alive:
                    continue
                for k, v in n.resources.available.items():
                    total[k] = total.get(k, 0.0) + v
            return total

    # -- actors --------------------------------------------------------------

    def register_actor(
        self, actor: "Actor", name: Optional[str], namespace: str
    ) -> None:
        with self._lock:
            if name:
                key = (namespace, name)
                if key in self._named_actors and self._named_actors[key] in self._actors:
                    existing = self._actors[self._named_actors[key]]
                    from ray_tpu.core.actor_runtime import ActorState

                    if existing.state != ActorState.DEAD:
                        raise ValueError(
                            f"actor name {name!r} already taken in namespace {namespace!r}"
                        )
                self._named_actors[key] = actor.actor_id
            self._actors[actor.actor_id] = actor

    def get_actor(self, actor_id: ActorID) -> Optional["Actor"]:
        with self._lock:
            return self._actors.get(actor_id)

    def get_named_actor(self, name: str, namespace: str) -> Optional["Actor"]:
        with self._lock:
            actor_id = self._named_actors.get((namespace, name))
            return self._actors.get(actor_id) if actor_id else None

    def remove_actor(self, actor_id: ActorID) -> None:
        with self._lock:
            actor = self._actors.pop(actor_id, None)
            if actor is not None:
                self._named_actors = {
                    k: v for k, v in self._named_actors.items() if v != actor_id
                }

    def list_actors(self) -> list["Actor"]:
        with self._lock:
            return list(self._actors.values())

    # -- placement groups ----------------------------------------------------

    def register_placement_group(self, pg) -> None:
        with self._lock:
            self._placement_groups[pg.id] = pg

    def remove_placement_group(self, pg_id) -> None:
        with self._lock:
            self._placement_groups.pop(pg_id, None)

    def list_placement_groups(self) -> list:
        with self._lock:
            return list(self._placement_groups.values())

    # -- internal KV (reference: gcs_kv_manager.h InternalKVManager) ---------

    def kv_put(self, key: bytes, value: bytes, namespace: str = "default") -> None:
        with self._lock:
            self._kv.setdefault(namespace, {})[key] = value

    def kv_get(self, key: bytes, namespace: str = "default") -> Optional[bytes]:
        with self._lock:
            return self._kv.get(namespace, {}).get(key)

    def kv_del(self, key: bytes, namespace: str = "default") -> None:
        with self._lock:
            self._kv.get(namespace, {}).pop(key, None)

    def kv_keys(self, prefix: bytes = b"", namespace: str = "default") -> list[bytes]:
        with self._lock:
            return [k for k in self._kv.get(namespace, {}) if k.startswith(prefix)]
