"""Public core API: init/remote/get/put/wait + actors + placement groups.

API-compatible in spirit with the reference's public surface
(python/ray/_private/worker.py:1285 init, :143-387 remote, :2645 get,
:2813 put, :2878 wait; python/ray/actor.py ActorClass/ActorHandle;
python/ray/util/placement_group.py), so a reference user can map their
program 1:1. Execution semantics differ where TPU-first design demands
it (thread workers in the host JAX process by default — see
core/scheduler.py docstring).
"""

from __future__ import annotations

import functools
import inspect
from typing import Any, Optional, Sequence, Union

from ray_tpu.core import errors, runtime as rt
from ray_tpu.core.actor_runtime import Actor, ActorState
from ray_tpu.core.placement import PlacementGroup, create_placement_group
from ray_tpu.core.ref import ObjectRef, ObjectRefGenerator
from ray_tpu.core.task import ActorOptions, TaskOptions
from ray_tpu.utils.ids import ActorID, ObjectID, TaskID

# Re-exported error types
from ray_tpu.core.errors import (  # noqa: F401
    ActorDiedError,
    GetTimeoutError,
    ObjectLostError,
    TaskCancelledError,
    TaskError,
    WorkerCrashedError,
)

__all__ = [
    "init",
    "shutdown",
    "is_initialized",
    "remote",
    "get",
    "put",
    "wait",
    "kill",
    "get_actor",
    "method",
    "cluster_resources",
    "available_resources",
    "placement_group",
    "remove_placement_group",
    "PlacementGroupSchedulingStrategy",
    "NodeAffinitySchedulingStrategy",
    "ObjectRef",
    "ObjectRefGenerator",
    "TaskError",
    "ActorDiedError",
    "GetTimeoutError",
]


# ---------------------------------------------------------------------------
# init / shutdown
# ---------------------------------------------------------------------------


# The cluster backend, when init(address=...) attached this driver to a
# GCS/node-daemon plane. One runtime per process: either in-process
# (threads in the host JAX process) or cluster (leases + worker
# processes), the same split as the reference's local vs address= init
# (python/ray/_private/worker.py:1285).
_CLUSTER: list = [None]


def init(
    *,
    address: Optional[str] = None,
    num_cpus: Optional[float] = None,
    num_tpus: Optional[float] = None,
    resources: Optional[dict] = None,
    worker_mode: Optional[str] = None,
    namespace: str = "default",
    ignore_reinit_error: bool = False,
):
    """Start the per-process runtime, or attach to a running cluster.

    With no `address`, boots the in-process runtime (single-node fast
    path). With `address="host:port"` (a GCS address), attaches this
    driver to that cluster: tasks/actors become leases on node daemons,
    executed in worker processes cluster-wide.

    `address="ray://host:port"` is accepted as an alias: the wire
    protocol is plain TCP RPC either way, so a driver OUTSIDE the
    cluster attaches exactly like a colocated one — the remote-client
    role the reference needs a separate gRPC proxy stack for
    (python/ray/_private/client_mode_hook.py, ray client server) is
    just the normal attach path here.
    """
    if address is not None:
        if address.startswith("ray://"):
            address = address[len("ray://"):]
        if _CLUSTER[0] is not None:
            if ignore_reinit_error:
                return _CLUSTER[0]
            raise RuntimeError(
                "ray_tpu.init(address=...) called twice; pass ignore_reinit_error=True"
            )
        from ray_tpu.core.cluster_backend import ClusterBackend

        _CLUSTER[0] = ClusterBackend(address, namespace=namespace)
        return _CLUSTER[0]
    if rt.is_initialized():
        if ignore_reinit_error:
            return rt.get_runtime()
        raise RuntimeError("ray_tpu.init() called twice; pass ignore_reinit_error=True")
    return rt.init_runtime(
        num_cpus=num_cpus,
        num_tpus=num_tpus,
        resources=resources,
        worker_mode=worker_mode,
        namespace=namespace,
    )


def shutdown() -> None:
    if _CLUSTER[0] is not None:
        _CLUSTER[0].close()
        _CLUSTER[0] = None
    rt.shutdown_runtime()


def is_initialized() -> bool:
    return _CLUSTER[0] is not None or rt.is_initialized()


def _auto_init() -> rt.Runtime:
    return rt.get_runtime()


def _cluster():
    """The attached ClusterBackend, or None (in-process mode)."""
    return _CLUSTER[0]


# ---------------------------------------------------------------------------
# scheduling strategies (reference: python/ray/util/scheduling_strategies.py)
# ---------------------------------------------------------------------------


class PlacementGroupSchedulingStrategy:
    def __init__(
        self,
        placement_group: PlacementGroup,
        placement_group_bundle_index: int = -1,
        placement_group_capture_child_tasks: bool = False,
    ):
        self.placement_group = placement_group
        self.placement_group_bundle_index = placement_group_bundle_index
        self.placement_group_capture_child_tasks = placement_group_capture_child_tasks


class NodeAffinitySchedulingStrategy:
    def __init__(self, node_id, soft: bool = False):
        self.node_id = node_id
        self.soft = soft


# ---------------------------------------------------------------------------
# remote functions
# ---------------------------------------------------------------------------

_TASK_OPTION_NAMES = {f.name for f in __import__("dataclasses").fields(TaskOptions)}
_ACTOR_OPTION_NAMES = {f.name for f in __import__("dataclasses").fields(ActorOptions)}


def _split_task_options(opts: dict) -> TaskOptions:
    unknown = set(opts) - _TASK_OPTION_NAMES - {"num_gpus"}
    if unknown:
        raise TypeError(f"unknown task options: {sorted(unknown)}")
    opts = {k: v for k, v in opts.items() if k in _TASK_OPTION_NAMES}
    return TaskOptions(**opts)


def _split_actor_options(opts: dict) -> ActorOptions:
    unknown = set(opts) - _ACTOR_OPTION_NAMES - {"num_gpus"}
    if unknown:
        raise TypeError(f"unknown actor options: {sorted(unknown)}")
    opts = {k: v for k, v in opts.items() if k in _ACTOR_OPTION_NAMES}
    return ActorOptions(**opts)


class RemoteFunction:
    """Wrapper returned by @remote on a function (reference:
    python/ray/remote_function.py:41)."""

    def __init__(self, func, options: Optional[TaskOptions] = None):
        self._func = func
        self._options = options or TaskOptions()
        functools.update_wrapper(self, func)

    def remote(self, *args, **kwargs):
        backend = _cluster()
        if backend is not None:
            out = backend.submit_task(self._func, args, kwargs, self._options)
            return out[0] if self._options.num_returns == 1 else out
        if self._options.runtime_env:
            raise ValueError(
                "runtime_env needs process-isolated workers: attach to a "
                "cluster first (ray_tpu.init(address=...))"
            )
        runtime = _auto_init()
        out = runtime.submit_task(self._func, args, kwargs, self._options)
        if isinstance(out, ObjectRefGenerator):
            return out
        if self._options.num_returns == 1:
            return out[0]
        return out

    def options(self, **opts) -> "RemoteFunction":
        import dataclasses

        # shallow field copy (asdict would deepcopy placement groups)
        merged = {
            f.name: getattr(self._options, f.name)
            for f in dataclasses.fields(self._options)
        }
        merged.update(opts)
        return RemoteFunction(self._func, _split_task_options(merged))

    def bind(self, *args, **kwargs):
        """Record a DAG node for workflows/compiled graphs (reference:
        FunctionNode via ray.dag)."""
        from ray_tpu.dag.nodes import FunctionNode

        return FunctionNode(self, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"remote function {self._func.__name__} cannot be called directly; "
            f"use .remote()"
        )


# ---------------------------------------------------------------------------
# actors
# ---------------------------------------------------------------------------


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str, num_returns: Union[int, str] = 1):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns

    def remote(self, *args, **kwargs):
        return self._handle._invoke(self._name, args, kwargs, self._num_returns)

    def options(self, num_returns: Union[int, str] = 1) -> "ActorMethod":
        return ActorMethod(self._handle, self._name, num_returns)

    def bind(self, *args, **kwargs):
        """Record a compiled-graph node instead of submitting (reference:
        python/ray/dag — actor.method.bind)."""
        from ray_tpu.dag.nodes import ClassMethodNode

        return ClassMethodNode(self._handle, self._name, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"actor method {self._name} cannot be called directly; use .remote()"
        )


def method(num_returns: Union[int, str] = 1):
    """Per-method decorator (reference @ray.method)."""

    def deco(f):
        f._ray_tpu_num_returns = num_returns
        return f

    return deco


class ActorHandle:
    def __init__(self, actor: Actor, runtime: rt.Runtime):
        object.__setattr__(self, "_actor", actor)
        object.__setattr__(self, "_runtime", runtime)

    def _invoke(self, method_name: str, args, kwargs, num_returns=1):
        runtime: rt.Runtime = self._runtime
        actor: Actor = self._actor
        task_id = TaskID.from_random()
        streaming = num_returns == "streaming"
        n = 1 if streaming else int(num_returns)
        from ray_tpu.core.task import TaskSpec
        from ray_tpu.obs import context as trace_context

        ctx = trace_context.current()
        spec = TaskSpec(
            task_id=task_id,
            func=actor.cls,  # carrier for describe(); not called
            args=args,
            kwargs=kwargs,
            options=TaskOptions(num_cpus=0, num_returns=num_returns, name=actor.cls.__name__),
            return_ids=[ObjectID.for_task_return(task_id, i) for i in range(n)],
            actor_id=actor.actor_id,
            method_name=method_name,
            streaming=streaming,
            trace=ctx.to_dict() if ctx is not None else None,
        )
        runtime._retain_arg_refs(spec)
        with runtime._lock:
            runtime._pending_tasks.add(task_id)
        from ray_tpu.core.events import TaskState

        runtime.task_events.record(
            task_id, spec.describe(), TaskState.SUBMITTED,
            kind="actor_task", actor_id=actor.actor_id,
        )
        if streaming:
            gen = ObjectRefGenerator(runtime, spec.describe())
            runtime.streaming_generators[task_id] = gen
            actor.submit(spec)
            return gen
        refs = [ObjectRef(rid, runtime, spec.describe()) for rid in spec.return_ids]
        actor.submit(spec)
        return refs[0] if n == 1 else refs

    def __getattr__(self, name: str):
        actor: Actor = object.__getattribute__(self, "_actor")
        target = getattr(actor.cls, name, None)
        if target is None or not callable(target):
            raise AttributeError(f"actor {actor.cls.__name__} has no method {name!r}")
        num_returns = getattr(target, "_ray_tpu_num_returns", 1)
        return ActorMethod(self, name, num_returns)

    @property
    def state(self) -> str:
        return self._actor.state

    def __repr__(self):
        a: Actor = self._actor
        return f"ActorHandle({a.cls.__name__}, {a.actor_id.hex()[:8]})"

    def __reduce__(self):
        return (_rebuild_actor_handle, (self._actor.actor_id,))

    def __del__(self):
        try:
            actor: Actor = object.__getattribute__(self, "_actor")
            runtime: rt.Runtime = object.__getattribute__(self, "_runtime")
        except Exception:
            return
        try:
            _on_handle_dropped(runtime, actor)
        except Exception:
            pass


def _rebuild_actor_handle(actor_id: ActorID) -> ActorHandle:
    runtime = rt.get_runtime()
    actor = runtime.gcs.get_actor(actor_id)
    if actor is None:
        raise errors.ActorDiedError(f"actor {actor_id} no longer exists")
    actor.num_handles += 1
    return ActorHandle(actor, runtime)


def _on_handle_dropped(runtime: rt.Runtime, actor: Actor) -> None:
    actor.num_handles -= 1
    if actor.num_handles <= 0 and actor.options.lifetime != "detached":
        # all handles gone: terminate (reference: actor GC on handle count)
        actor.kill(no_restart=True)
        runtime.gcs.remove_actor(actor.actor_id)


class ActorClass:
    """Wrapper returned by @remote on a class (reference:
    python/ray/actor.py:605)."""

    def __init__(self, cls: type, options: Optional[ActorOptions] = None):
        self._cls = cls
        self._options = options or ActorOptions()
        functools.update_wrapper(self, cls, updated=[])

    def remote(self, *args, **kwargs) -> ActorHandle:
        backend = _cluster()
        if backend is not None:
            return backend.create_actor(self._cls, args, kwargs, self._options)
        if self._options.runtime_env:
            raise ValueError(
                "runtime_env needs process-isolated workers: attach to a "
                "cluster first (ray_tpu.init(address=...))"
            )
        runtime = _auto_init()
        opts = self._options
        if opts.name:
            existing = runtime.gcs.get_named_actor(opts.name, runtime.namespace)
            if existing is not None and existing.state != ActorState.DEAD:
                if opts.get_if_exists:
                    existing.num_handles += 1
                    return ActorHandle(existing, runtime)
                # check BEFORE acquiring resources/running the ctor, so a
                # name collision can't leak a live actor + its reservation
                raise ValueError(
                    f"actor name {opts.name!r} already taken in namespace "
                    f"{runtime.namespace!r}"
                )
        # actor resources are held for the actor's lifetime
        from ray_tpu.core.scheduler import resolve_pool

        pool, req = resolve_pool(runtime, opts)
        if not pool.try_acquire(req):
            raise errors.RayTpuError(
                f"cannot create actor {self._cls.__name__}: resources {dict(req)} "
                f"unavailable (available: {dict(pool.available)})"
            )
        actor = Actor(
            runtime, ActorID.from_random(), self._cls, args, kwargs, opts
        )
        actor._resource_pool = pool
        actor._resource_req = req
        if actor.state == ActorState.DEAD:
            # ctor already failed before we attached the reservation
            actor._release_resources()
        try:
            runtime.gcs.register_actor(actor, opts.name, runtime.namespace)
        except Exception:
            # registration race lost: tear the orphan down, free resources
            actor.kill(no_restart=True)
            raise
        return ActorHandle(actor, runtime)

    def options(self, **opts) -> "ActorClass":
        import dataclasses

        merged = {
            f.name: getattr(self._options, f.name)
            for f in dataclasses.fields(self._options)
        }
        merged.update(opts)
        return ActorClass(self._cls, _split_actor_options(merged))

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"actor class {self._cls.__name__} cannot be instantiated directly; "
            f"use .remote()"
        )


# ---------------------------------------------------------------------------
# the @remote decorator
# ---------------------------------------------------------------------------


def remote(*args, **kwargs):
    """@remote / @remote(num_cpus=..., resources=..., ...) on fn or class."""
    if len(args) == 1 and not kwargs and (callable(args[0]) or inspect.isclass(args[0])):
        target = args[0]
        if inspect.isclass(target):
            return ActorClass(target)
        return RemoteFunction(target)
    if args:
        raise TypeError("@remote takes keyword options only")

    def deco(target):
        if inspect.isclass(target):
            return ActorClass(target, _split_actor_options(kwargs))
        return RemoteFunction(target, _split_task_options(kwargs))

    return deco


# ---------------------------------------------------------------------------
# object API
# ---------------------------------------------------------------------------


def put(value: Any) -> ObjectRef:
    backend = _cluster()
    if backend is not None:
        return backend.put(value)
    return _auto_init().put(value)


def get(refs, timeout: Optional[float] = None):
    backend = _cluster()
    if backend is not None and not isinstance(refs, ObjectRef):
        return backend.get(refs, timeout=timeout)
    runtime = _auto_init()
    if isinstance(refs, ObjectRef):
        return runtime.get([refs], timeout)[0]
    return runtime.get(list(refs), timeout)


def wait(
    refs: Sequence[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: Optional[float] = None,
    fetch_local: bool = True,
):
    if not refs:
        return [], []
    backend = _cluster()
    if backend is not None and not isinstance(refs[0], ObjectRef):
        return backend.wait(list(refs), num_returns, timeout)
    return _auto_init().wait(list(refs), num_returns, timeout)


def free(refs) -> None:
    """Explicitly delete objects from the store(s) (reference:
    ray._private.internal_api.free). Useful for fire-and-forget acks in
    long-running loops — especially from worker processes, which borrow
    rather than own and so never auto-free."""
    if not isinstance(refs, (list, tuple)):
        refs = [refs]
    backend = _cluster()
    if backend is not None and refs and not isinstance(refs[0], ObjectRef):
        backend.client.free(list(refs))
        return
    runtime = _auto_init()
    for r in refs:
        # drop the producer's primary reference; the entry frees when the
        # remaining handle refs release
        runtime.object_store.remove_ref(r.id)


def kill(handle, *, no_restart: bool = True) -> None:
    if hasattr(handle, "_actor"):  # in-process handle
        handle._actor.kill(no_restart=no_restart)
    else:  # ClusterActorHandle
        handle.kill()


def get_actor(name: str, namespace: Optional[str] = None):
    backend = _cluster()
    if backend is not None:
        return backend.get_named_actor(name, namespace)
    runtime = _auto_init()
    actor = runtime.gcs.get_named_actor(name, namespace or runtime.namespace)
    if actor is None or actor.state == ActorState.DEAD:
        raise ValueError(f"named actor {name!r} not found")
    actor.num_handles += 1
    return ActorHandle(actor, runtime)


def cluster_resources() -> dict:
    backend = _cluster()
    if backend is not None:
        return backend.cluster_resources()
    return _auto_init().gcs.cluster_resources()


def available_resources() -> dict:
    backend = _cluster()
    if backend is not None:
        return backend.available_resources()
    return _auto_init().gcs.available_resources()


# ---------------------------------------------------------------------------
# placement groups
# ---------------------------------------------------------------------------


def placement_group(
    bundles: list[dict],
    strategy: str = "PACK",
    name: str = "",
) -> PlacementGroup:
    backend = _cluster()
    if backend is not None:
        return backend.placement_group(bundles, strategy, name)
    runtime = _auto_init()
    pg = create_placement_group(runtime, bundles, strategy, name)
    runtime.gcs.register_placement_group(pg)
    return pg


def remove_placement_group(pg) -> None:
    backend = _cluster()
    if backend is not None and not isinstance(pg, PlacementGroup):
        backend.remove_placement_group(pg)
        return
    runtime = _auto_init()
    pg.remove()
    runtime.gcs.remove_placement_group(pg.id)
