"""Resource accounting: the currency of scheduling.

Analog of the reference's ResourceSet/NodeResources machinery
(src/ray/common/scheduling/resource_set.h and
src/ray/raylet/scheduling/local_resource_manager.*) with the TPU twist
baked in: every node advertises `TPU` chips, and slice-gang resources
("TPU-{pod}-head", "{slice_name}") are plain custom resources, exactly
the pattern the reference's TPU plugin established
(python/ray/_private/accelerators/tpu.py:330-393).
"""

from __future__ import annotations

import threading
from typing import Optional

EPS = 1e-9


class ResourceSet(dict):
    """{resource_name: float}. Missing key == 0."""

    def __init__(self, mapping: Optional[dict] = None, **kwargs):
        super().__init__()
        for k, v in {**(mapping or {}), **kwargs}.items():
            if v < 0:
                raise ValueError(f"negative resource {k}={v}")
            if v > 0:
                self[k] = float(v)

    def fits_in(self, other: "ResourceSet") -> bool:
        return all(other.get(k, 0.0) + EPS >= v for k, v in self.items())

    def add(self, other: "ResourceSet") -> "ResourceSet":
        out = ResourceSet(self)
        for k, v in other.items():
            out[k] = out.get(k, 0.0) + v
        return out

    def subtract(self, other: "ResourceSet") -> "ResourceSet":
        out = ResourceSet(self)
        for k, v in other.items():
            nv = out.get(k, 0.0) - v
            if nv < -EPS:
                raise ValueError(f"resource {k} would go negative ({nv})")
            if abs(nv) < EPS:
                out.pop(k, None)
            else:
                out[k] = nv
        return out


class NodeResources:
    """Thread-safe available/total tracking for one node."""

    def __init__(self, total: ResourceSet):
        self.total = ResourceSet(total)
        self._available = ResourceSet(total)
        self._lock = threading.Lock()

    def try_acquire(self, req: ResourceSet) -> bool:
        with self._lock:
            if not req.fits_in(self._available):
                return False
            self._available = self._available.subtract(req)
            return True

    def release(self, req: ResourceSet) -> None:
        with self._lock:
            self._available = self._available.add(req)

    def add_capacity(self, extra: ResourceSet) -> None:
        """Dynamically grow totals (used by placement-group bundle resources)."""
        with self._lock:
            self.total = self.total.add(extra)
            self._available = self._available.add(extra)

    def remove_capacity(self, extra: ResourceSet) -> None:
        with self._lock:
            self.total = self.total.subtract(extra)
            self._available = self._available.subtract(extra)

    @property
    def available(self) -> ResourceSet:
        with self._lock:
            return ResourceSet(self._available)

    def in_use(self) -> ResourceSet:
        with self._lock:
            out = ResourceSet()
            for k, v in self.total.items():
                used = v - self._available.get(k, 0.0)
                if used > EPS:
                    out[k] = used
            return out

    def utilization(self) -> float:
        with self._lock:
            if not self.total:
                return 0.0
            fracs = [
                1.0 - self._available.get(k, 0.0) / v
                for k, v in self.total.items()
                if v > 0
            ]
            return max(fracs) if fracs else 0.0
