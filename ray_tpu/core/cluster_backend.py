"""Cluster backend for the public API: one `init(address=...)` attaches
the whole `ray_tpu.*` surface to a running GCS/node-daemon plane.

Reference analog: ray.init(address=...) attaching the driver's core
worker to an existing GCS + raylet (python/ray/_private/worker.py:1285);
after that every `remote/get/put/wait/actor/placement_group` call rides
the same cluster runtime that Train/Serve/Data workers use. Here the
adapter maps the in-process API's TaskOptions/ActorOptions onto the
ClusterClient protocol (leases, pushes, GCS actor table) so the SAME
user program runs in-process (no address) or on a multi-process cluster
(address given) without edits.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

from ray_tpu.cluster.client import (
    ClusterActorHandle,
    ClusterClient,
    ClusterObjectRef,
)
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.core.cluster_backend")


class ClusterPlacementGroup:
    """Placement-group handle in cluster mode (reference:
    python/ray/util/placement_group.py:41 PlacementGroup)."""

    def __init__(self, info: dict, client: ClusterClient):
        self._info = info
        self._client = client

    @property
    def id(self) -> bytes:
        return self._info["pg_id"]

    @property
    def bundle_specs(self) -> list[dict]:
        return [dict(b["resources"]) for b in self._info["bundles"]]

    @property
    def bundles(self) -> list[dict]:
        return [dict(b) for b in self._info["bundles"]]

    def ready(self, timeout: float = 30.0) -> bool:
        # create_placement_group blocks until CREATED + reserved, so a
        # constructed handle is ready by definition; re-check for liveness
        info = self._client.gcs.call("get_pg", {"pg_id": self.id})
        return info is not None and info["state"] == "CREATED"

    def remove(self) -> None:
        self._client.remove_placement_group(self.id)

    def __repr__(self) -> str:
        return f"ClusterPlacementGroup({self.id.hex()[:12]}, {len(self._info['bundles'])} bundles)"


def _to_cluster_resources(options) -> dict:
    """Map TaskOptions/ActorOptions resources onto the cluster's resource
    naming (daemons register `num_cpus`, `TPU`, plus custom keys)."""
    req = dict(options.resources)
    if options.num_cpus:
        req["num_cpus"] = req.get("num_cpus", 0.0) + options.num_cpus
    if options.num_tpus:
        req["TPU"] = req.get("TPU", 0.0) + options.num_tpus
    return req


def _placement(options) -> tuple[Optional[bytes], int, Optional[str], bool]:
    """Extract (pg_id, bundle_index, affinity_node_id, affinity_soft)
    from options + scheduling strategy (single source of truth, the
    cluster-mode analog of core/scheduler.resolve_pool)."""
    pg = options.placement_group
    idx = options.placement_group_bundle_index
    affinity = None
    soft = False
    strat = options.scheduling_strategy
    if strat is not None and hasattr(strat, "placement_group"):
        pg = strat.placement_group
        idx = strat.placement_group_bundle_index
    elif strat is not None and hasattr(strat, "node_id"):
        affinity = strat.node_id
        soft = bool(getattr(strat, "soft", False))
    pg_id = None
    if pg is not None:
        pg_id = getattr(pg, "id", None)
        if isinstance(pg_id, (bytearray, memoryview)):
            pg_id = bytes(pg_id)
        if not isinstance(pg_id, bytes):
            raise TypeError(
                f"cluster mode needs a ClusterPlacementGroup (got {type(pg).__name__}); "
                "create it via ray_tpu.placement_group() after init(address=...)"
            )
    # -1 = "any bundle that fits" (wildcard), resolved at lease time
    bundle_index = -1 if idx is None or idx < 0 else int(idx)
    return pg_id, bundle_index, affinity, soft


class ClusterBackend:
    """Adapter: public-API calls -> ClusterClient protocol."""

    @classmethod
    def from_client(cls, client: ClusterClient,
                    namespace: str = "default") -> "ClusterBackend":
        """Wrap an existing ClusterClient (worker processes: their
        ambient client already points at the local daemon)."""
        self = cls.__new__(cls)
        self.client = client
        self.namespace = namespace
        self.address = "%s:%d" % client.gcs.addr
        return self

    def __init__(self, address: str, namespace: str = "default"):
        # "h:p" (single GCS) or "h1:p1,h2:p2" (HA pair: primary first,
        # standby second — calls fail over on primary death)
        from ray_tpu.cluster.rpc import ReconnectingRpcClient, parse_gcs_addr

        gcs_addr = parse_gcs_addr(address)
        # the driver leases from / fetches through a colocated daemon; on
        # a LocalCluster every daemon is local, so attach to the first
        # alive node (reference: ray.init picks up the local raylet)
        gcs = ReconnectingRpcClient(
            *gcs_addr, timeout=60.0
        ).connect(retries=20)
        nodes = [n for n in gcs.call("list_nodes", None) if n["alive"]]
        gcs.close()
        if not nodes:
            raise ConnectionError(
                f"no alive nodes registered at GCS {address}; start a node "
                "daemon first (LocalCluster.add_node or ray_tpu.cluster CLI)"
            )
        self.client = ClusterClient(gcs_addr, tuple(nodes[0]["addr"]))
        self.namespace = namespace
        self.address = address

    def close(self) -> None:
        self.client.close()

    # -- tasks ---------------------------------------------------------------

    def submit_task(self, func, args, kwargs, options) -> list[ClusterObjectRef]:
        if options.num_returns == "streaming":
            raise NotImplementedError(
                "streaming generators are not yet supported in cluster mode"
            )
        pg_id, bundle_index, affinity, soft = _placement(options)
        out = self.client.submit(
            func,
            args,
            dict(kwargs or {}),
            resources=_to_cluster_resources(options),
            num_returns=int(options.num_returns),
            max_retries=options.max_retries,
            pg_id=pg_id,
            bundle_index=bundle_index,
            desc=options.name or getattr(func, "__name__", "task"),
            affinity_node_id=affinity,
            affinity_soft=soft,
            runtime_env=options.runtime_env,
        )
        return out if isinstance(out, list) else [out]

    # -- actors --------------------------------------------------------------

    def create_actor(self, cls, args, kwargs, options) -> ClusterActorHandle:
        if options.name and options.get_if_exists:
            try:
                return self.client.get_named_actor(options.name, self.namespace)
            except ValueError:
                pass
        pg_id, bundle_index, _affinity, _soft = _placement(options)
        return self.client.create_actor(
            cls,
            args,
            dict(kwargs or {}),
            resources=_to_cluster_resources(options),
            name=options.name,
            namespace=self.namespace,
            max_restarts=options.max_restarts,
            pg_id=pg_id,
            bundle_index=bundle_index,
            runtime_env=options.runtime_env,
        )

    def get_named_actor(self, name: str, namespace: Optional[str] = None):
        return self.client.get_named_actor(name, namespace or self.namespace)

    # -- objects -------------------------------------------------------------

    def put(self, value: Any) -> ClusterObjectRef:
        return self.client.put(value)

    def get(self, refs, timeout: Optional[float] = None):
        return self.client.get(refs, timeout=timeout)

    def wait(self, refs: Sequence[ClusterObjectRef], num_returns: int,
             timeout: Optional[float]):
        return self.client.wait(refs, num_returns=num_returns, timeout=timeout)

    # -- placement groups ----------------------------------------------------

    def placement_group(self, bundles: list[dict], strategy: str,
                        name: str = "") -> ClusterPlacementGroup:
        # accept in-process style bundle dicts ({"CPU": 1} or {"num_cpus": 1})
        norm = []
        for b in bundles:
            r = dict(b)
            if "CPU" in r:
                r["num_cpus"] = r.pop("CPU")
            norm.append(r)
        info = self.client.create_placement_group(
            norm, strategy=strategy, name=name or None
        )
        return ClusterPlacementGroup(info, self.client)

    def remove_placement_group(self, pg) -> None:
        pg_id = pg.id if hasattr(pg, "id") else pg
        self.client.remove_placement_group(pg_id)

    # -- cluster state -------------------------------------------------------

    def cluster_resources(self) -> dict:
        return self.client.cluster_resources()

    def available_resources(self) -> dict:
        total: dict[str, float] = {}
        for n in self.client.nodes():
            if n["alive"]:
                for k, v in n["available"].items():
                    total[k] = total.get(k, 0.0) + v
        return total

    def nodes(self) -> list:
        return self.client.nodes()
