"""Placement groups: gang resource reservation.

Analog of the reference's placement groups
(python/ray/util/placement_group.py:41,145; bundle packing policies in
src/ray/raylet/scheduling/policy/bundle_scheduling_policy.cc; strategy
enum src/ray/protobuf/common.proto:978-985). The TPU-first reading:
STRICT_PACK = one ICI sub-slice (all bundles on one host group),
STRICT_SPREAD = one bundle per host of a pod slice — this is the gang
mechanism `slice_run` uses to SPMD a jitted program across hosts.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Optional

from ray_tpu.core import errors
from ray_tpu.core.resources import NodeResources, ResourceSet
from ray_tpu.utils.ids import PlacementGroupID

if TYPE_CHECKING:
    from ray_tpu.core.runtime import Runtime

STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


@dataclass
class Bundle:
    index: int
    resources: ResourceSet
    node_id: Optional[object] = None  # which node holds the reservation
    pool: Optional[NodeResources] = None  # per-bundle accounting


class PlacementGroup:
    def __init__(
        self,
        pg_id: PlacementGroupID,
        bundles: list[dict],
        strategy: str,
        name: str,
        runtime: "Runtime",
    ):
        if strategy not in STRATEGIES:
            raise ValueError(f"unknown strategy {strategy!r}; one of {STRATEGIES}")
        self.id = pg_id
        self.strategy = strategy
        self.name = name
        self._runtime = runtime
        self.bundles = [Bundle(i, ResourceSet(b)) for i, b in enumerate(bundles)]
        self._state = "PENDING"
        self._infeasible_reason: Optional[str] = None
        self._lock = threading.Lock()
        # serializes whole reservation attempts (autoscaler retry vs
        # cluster.add_node retry vs creation) — _lock only guards state reads
        self._reserve_lock = threading.Lock()

    @property
    def bundle_specs(self) -> list[dict]:
        return [dict(b.resources) for b in self.bundles]

    def mark_created(self) -> None:
        with self._lock:
            self._state = "CREATED"

    def mark_infeasible(self, reason: str) -> None:
        with self._lock:
            self._state = "INFEASIBLE"
            self._infeasible_reason = reason

    def ready(self, timeout: Optional[float] = 30.0) -> bool:
        """Block until the reservation exists (reference pg.ready() is an
        ObjectRef that stays pending). INFEASIBLE is not terminal while an
        autoscaler/cluster may add nodes — poll until the deadline, THEN
        raise if still infeasible; return False if merely pending."""
        import time as _time

        deadline = _time.monotonic() + (timeout if timeout is not None else 0.0)
        infinite = timeout is None
        while True:
            with self._lock:
                state, reason = self._state, self._infeasible_reason
            if state == "CREATED":
                return True
            if state == "REMOVED":
                raise errors.PlacementGroupUnavailableError(
                    f"placement group {self.name or self.id} was removed"
                )
            if not infinite and _time.monotonic() >= deadline:
                if state == "INFEASIBLE":
                    raise errors.PlacementGroupUnavailableError(
                        f"placement group {self.name or self.id}: {reason}"
                    )
                return False
            _time.sleep(0.02)

    def bundle_pool(self, index: int, req: ResourceSet) -> NodeResources:
        """Resolve which bundle's reservation a task draws from."""
        with self._lock:
            if self._state == "INFEASIBLE":
                raise errors.PlacementGroupUnavailableError(
                    f"placement group {self.name or self.id}: {self._infeasible_reason}"
                )
            if self._state == "REMOVED":
                raise errors.PlacementGroupUnavailableError(
                    f"placement group {self.name or self.id} was removed"
                )
        if index >= 0:
            if index >= len(self.bundles):
                raise errors.PlacementGroupUnavailableError(
                    f"bundle index {index} out of range ({len(self.bundles)} bundles)"
                )
            return self.bundles[index].pool
        # wildcard: first bundle that currently fits, else bundle 0 (task
        # will queue until that bundle frees up)
        for b in self.bundles:
            if req.fits_in(b.pool.available):
                return b.pool
        return self.bundles[0].pool

    def remove(self) -> None:
        """Reject new work immediately; release node capacity once in-flight
        bundle tasks drain (running threads can't be killed; the reference
        instead kills PG workers — raylet PlacementGroupResourceManager)."""
        with self._reserve_lock:
            self._remove_locked()

    def _remove_locked(self) -> None:
        with self._lock:
            if self._state == "REMOVED":
                return
            prev, self._state = self._state, "REMOVED"
        if prev != "CREATED":
            return

        def _drain_and_release():
            import time as _time

            for b in self.bundles:
                if b.node_id is None:
                    continue
                while b.pool is not None and b.pool.in_use():
                    _time.sleep(0.05)
                node = self._runtime.gcs.get_node(b.node_id)
                if node is not None:
                    node.resources.release(b.resources)
            self._runtime.scheduler.notify()

        threading.Thread(
            target=_drain_and_release, name="ray_tpu-pg-drain", daemon=True
        ).start()

    def __repr__(self):
        return f"PlacementGroup({self.name or self.id.hex()[:8]}, {self.strategy}, {len(self.bundles)} bundles, {self._state})"


def create_placement_group(
    runtime: "Runtime",
    bundles: list[dict],
    strategy: str = "PACK",
    name: str = "",
) -> PlacementGroup:
    """Reserve bundle resources on cluster nodes per the strategy."""
    if not bundles:
        raise ValueError("placement group needs at least one bundle")
    pg = PlacementGroup(PlacementGroupID.from_random(), bundles, strategy, name, runtime)
    return reserve_placement_group(pg, runtime.gcs.alive_nodes())


def retry_pending_placement_groups(runtime: "Runtime") -> None:
    """Re-attempt reservation for every PENDING/INFEASIBLE group (called
    by the autoscaler and cluster_utils after adding nodes)."""
    nodes = runtime.gcs.alive_nodes()
    for pg in runtime.gcs.list_placement_groups():
        if getattr(pg, "_state", None) in ("PENDING", "INFEASIBLE"):
            reserve_placement_group(pg, nodes)


def reserve_placement_group(pg: PlacementGroup, nodes: list) -> PlacementGroup:
    """Try to reserve a PENDING/INFEASIBLE group's bundles. Separated from
    creation so the autoscaler can retry after adding nodes (the reference
    keeps pending PGs queued in GcsPlacementGroupManager and retries on
    node add)."""
    with pg._reserve_lock:
        return _reserve_locked(pg, nodes)


def _reserve_locked(pg: PlacementGroup, nodes: list) -> PlacementGroup:
    with pg._lock:
        if pg._state in ("CREATED", "REMOVED"):
            return pg  # REMOVED is terminal: never resurrect a removed group
        pg._state = "PENDING"
        pg._infeasible_reason = None
    strategy = pg.strategy

    def reserve(bundle: Bundle, node) -> bool:
        if node.resources.try_acquire(bundle.resources):
            bundle.node_id = node.node_id
            # Per-bundle pool so tasks draw from the reservation, mirroring
            # the reference's CPU_group_{pg_id} shadow resources.
            bundle.pool = NodeResources(bundle.resources)
            return True
        return False

    reserved: list[tuple[Bundle, object]] = []

    def rollback() -> None:
        for b, node in reserved:
            node.resources.release(b.resources)
            b.node_id, b.pool = None, None

    if strategy in ("PACK", "STRICT_PACK"):
        # all bundles on one node if possible (PACK falls back to spill)
        for node in nodes:
            ok = True
            for b in pg.bundles:
                if reserve(b, node):
                    reserved.append((b, node))
                else:
                    ok = False
                    break
            if ok:
                pg.mark_created()
                return pg
            rollback()
            reserved.clear()
        if strategy == "STRICT_PACK":
            pg.mark_infeasible("no single node can hold all bundles (STRICT_PACK)")
            return pg
        # PACK fallback: best-effort any placement
        strategy = "SPREAD"

    if strategy in ("SPREAD", "STRICT_SPREAD"):
        used_nodes: set = set()
        for b in pg.bundles:
            placed = False
            # prefer unused nodes (spread), then any (non-strict)
            candidates = [n for n in nodes if n.node_id not in used_nodes]
            if strategy == "SPREAD":
                candidates += [n for n in nodes if n.node_id in used_nodes]
            for node in candidates:
                if reserve(b, node):
                    reserved.append((b, node))
                    used_nodes.add(node.node_id)
                    placed = True
                    break
            if not placed:
                rollback()
                pg.mark_infeasible(
                    f"bundle {b.index} ({dict(b.resources)}) does not fit "
                    f"({strategy}; {len(nodes)} nodes)"
                )
                return pg
        pg.mark_created()
        return pg

    raise AssertionError(f"unhandled strategy {strategy}")
