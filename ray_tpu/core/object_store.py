"""Host object store: in-process memory store + shared-memory segments.

TPU-native rethink of the reference's two-tier store (in-process memory
store for small objects + plasma shared memory for large ones —
reference: src/ray/core_worker/memory_store/ and
src/ray/object_manager/plasma/object_store.h:74). Key design change:
because a TPU host runs ONE JAX process (chips are single-owner), the
default execution mode is threads inside that process, and the fast path
for objects is a *reference* — zero serialization, zero copy. Shared
memory (`multiprocessing.shared_memory` today, the C++ slab store when
built) is used only when crossing a process boundary, with numpy arrays
carried out-of-band so reconstruction is a zero-copy mmap view (the
plasma + pickle5-buffers behavior of the reference,
python/ray/_private/serialization.py).
"""

from __future__ import annotations

import pickle
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Optional

import cloudpickle
import numpy as np

from ray_tpu.core.errors import GetTimeoutError, ObjectLostError
from ray_tpu.utils.ids import ObjectID


@dataclass
class _Entry:
    """ref_count semantics: starts at 0 for placeholder entries (waiters,
    tombstones); the producing put() adds the primary reference. A negative
    count is a tombstone — the owner ObjectRef died before production, so
    the value is dropped the moment it lands (fire-and-forget tasks must
    not leak, reference analog: ReferenceCounter ownership release)."""

    value: Any = None
    serialized: Optional[tuple[bytes, list]] = None  # (payload, oob buffers)
    ready: threading.Event = field(default_factory=threading.Event)
    error: Optional[BaseException] = None
    ref_count: int = 0
    nbytes: int = 0


def serialize(value: Any) -> tuple[bytes, list[np.ndarray]]:
    """cloudpickle with out-of-band numpy buffers (zero-copy reconstruct)."""
    buffers: list = []
    payload = cloudpickle.dumps(value, protocol=5, buffer_callback=buffers.append)
    return payload, [np.frombuffer(b, dtype=np.uint8) for b in buffers]


def deserialize(payload: bytes, buffers: list) -> Any:
    return pickle.loads(payload, buffers=[b.data if hasattr(b, "data") else b for b in buffers])


class ObjectStore:
    """Per-node store. Thread-safe. Values stored by reference (thread mode
    fast path); `serialized_get` materializes bytes for process/DCN transport."""

    def __init__(self, capacity_bytes: int = 0):
        self._entries: dict[ObjectID, _Entry] = {}
        self._lock = threading.Lock()
        self._capacity = capacity_bytes  # 0 = unbounded (host RAM)
        self._used = 0
        self._on_ready: dict[ObjectID, list[Callable[[ObjectID], None]]] = {}

    # -- write paths ---------------------------------------------------------

    def put(self, obj_id: ObjectID, value: Any) -> None:
        with self._lock:
            entry = self._entries.setdefault(obj_id, _Entry())
            entry.ref_count += 1  # the producer's primary reference
            entry.value = value
            entry.nbytes = _estimate_nbytes(value)
            self._used += entry.nbytes
            entry.ready.set()
            callbacks = self._on_ready.pop(obj_id, [])
            self._maybe_free_locked(obj_id, entry)
        for cb in callbacks:
            cb(obj_id)

    def put_error(self, obj_id: ObjectID, error: BaseException) -> None:
        with self._lock:
            entry = self._entries.setdefault(obj_id, _Entry())
            entry.ref_count += 1
            entry.error = error
            entry.ready.set()
            callbacks = self._on_ready.pop(obj_id, [])
            self._maybe_free_locked(obj_id, entry)
        for cb in callbacks:
            cb(obj_id)

    def put_serialized(self, obj_id: ObjectID, payload: bytes, buffers: list) -> None:
        with self._lock:
            entry = self._entries.setdefault(obj_id, _Entry())
            entry.ref_count += 1
            entry.serialized = (payload, buffers)
            entry.nbytes = len(payload) + sum(getattr(b, "nbytes", len(b)) for b in buffers)
            self._used += entry.nbytes
            entry.ready.set()
            callbacks = self._on_ready.pop(obj_id, [])
            self._maybe_free_locked(obj_id, entry)
        for cb in callbacks:
            cb(obj_id)

    # -- read paths ----------------------------------------------------------

    def contains(self, obj_id: ObjectID) -> bool:
        with self._lock:
            e = self._entries.get(obj_id)
            return e is not None and e.ready.is_set()

    def get(self, obj_id: ObjectID, timeout: Optional[float] = None) -> Any:
        with self._lock:
            entry = self._entries.get(obj_id)
            if entry is None:
                # object not produced yet (pending task return): wait for it
                entry = _Entry(ref_count=0)
                entry.ready.clear()
                self._entries[obj_id] = entry
        if not entry.ready.wait(timeout):
            raise GetTimeoutError(f"timed out waiting for {obj_id}")
        if entry.error is not None:
            raise entry.error
        if entry.value is None and entry.serialized is not None:
            payload, buffers = entry.serialized
            entry.value = deserialize(payload, buffers)
        return entry.value

    def wait_async(self, obj_id: ObjectID, callback: Callable[[ObjectID], None]) -> None:
        """Invoke callback when the object is ready (immediately if already)."""
        with self._lock:
            entry = self._entries.get(obj_id)
            if entry is None or not entry.ready.is_set():
                self._on_ready.setdefault(obj_id, []).append(callback)
                if entry is None:
                    self._entries[obj_id] = _Entry(ref_count=0)
                    self._entries[obj_id].ready.clear()
                return
        callback(obj_id)

    def cancel_wait(self, obj_id: ObjectID, callback: Callable[[ObjectID], None]) -> None:
        """Deregister a wait_async callback (polling wait() must not leak)."""
        with self._lock:
            cbs = self._on_ready.get(obj_id)
            if cbs is None:
                return
            try:
                cbs.remove(callback)
            except ValueError:
                pass
            if not cbs:
                del self._on_ready[obj_id]

    def serialized_get(self, obj_id: ObjectID, timeout: Optional[float] = None) -> tuple[bytes, list]:
        value = self.get(obj_id, timeout)
        with self._lock:
            entry = self._entries[obj_id]
            if entry.serialized is None:
                entry.serialized = serialize(value)
            return entry.serialized

    # -- ref counting --------------------------------------------------------

    def add_ref(self, obj_id: ObjectID, n: int = 1) -> None:
        with self._lock:
            entry = self._entries.setdefault(obj_id, _Entry())
            entry.ref_count += n

    def remove_ref(self, obj_id: ObjectID, n: int = 1) -> None:
        with self._lock:
            entry = self._entries.get(obj_id)
            if entry is None:
                # ref died before the object was produced: tombstone so the
                # eventual put() frees the value immediately
                tomb = _Entry(ref_count=-n)
                self._entries[obj_id] = tomb
                return
            entry.ref_count -= n
            self._maybe_free_locked(obj_id, entry)

    def _maybe_free_locked(self, obj_id: ObjectID, entry: _Entry) -> None:
        if entry.ref_count <= 0 and entry.ready.is_set() and not self._on_ready.get(obj_id):
            self._used -= entry.nbytes
            self._entries.pop(obj_id, None)

    def stats(self) -> dict:
        with self._lock:
            return {
                "num_objects": len(self._entries),
                "used_bytes": self._used,
                "capacity_bytes": self._capacity,
            }


def _estimate_nbytes(value: Any) -> int:
    if isinstance(value, np.ndarray):
        return value.nbytes
    if isinstance(value, (bytes, bytearray)):
        return len(value)
    try:  # jax arrays, without assuming jax is importable here
        import jax

        if isinstance(value, jax.Array):
            return value.nbytes
    except Exception:
        pass
    return 64  # nominal for small python objects
