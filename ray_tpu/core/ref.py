"""ObjectRef: a first-class future naming an object in the cluster.

Analog of the reference ObjectRef (python/ray/_raylet.pyx ObjectRef +
ownership in src/ray/core_worker/reference_count.h:66): the creating
process owns the object and its lifetime; refs are reference-counted and
the store entry is freed when the last ref drops.
"""

from __future__ import annotations

import threading
from typing import TYPE_CHECKING, Any, Optional

from ray_tpu.utils.ids import ObjectID

if TYPE_CHECKING:
    from ray_tpu.core.runtime import Runtime


class ObjectRef:
    __slots__ = ("id", "_runtime", "_task_desc", "__weakref__")

    def __init__(self, obj_id: ObjectID, runtime: "Runtime", task_desc: str = ""):
        self.id = obj_id
        self._runtime = runtime
        self._task_desc = task_desc

    def future(self):
        """concurrent.futures.Future resolving to the object's value."""
        import concurrent.futures

        fut: concurrent.futures.Future = concurrent.futures.Future()

        def _on_ready(_):
            try:
                fut.set_result(self._runtime.get([self], timeout=0)[0])
            except BaseException as e:  # noqa: BLE001 - propagate to future
                fut.set_exception(e)

        self._runtime.object_store.wait_async(self.id, _on_ready)
        return fut

    def hex(self) -> str:
        return self.id.hex()

    def __reduce__(self):
        # Serialized refs travel between workers of the same runtime; on
        # deserialization we re-attach to the process-local runtime.
        self._runtime.on_ref_serialized(self.id)
        return (_rebuild_ref, (self.id, self._task_desc))

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ObjectRef({self.id.hex()[:12]}{', ' + self._task_desc if self._task_desc else ''})"

    def __del__(self):
        runtime = getattr(self, "_runtime", None)
        if runtime is not None:
            try:
                runtime.on_ref_deleted(self.id)
            except Exception:
                pass

    def __await__(self):
        import asyncio

        return asyncio.wrap_future(self.future()).__await__()


def _rebuild_ref(obj_id: ObjectID, task_desc: str) -> ObjectRef:
    from ray_tpu.core.runtime import get_runtime

    return ObjectRef(obj_id, get_runtime(), task_desc)


class ObjectRefGenerator:
    """Streaming returns: iterate refs as the task yields them (analog of
    reference ObjectRefGenerator, python/ray/_raylet.pyx:294)."""

    def __init__(self, runtime: "Runtime", task_desc: str = ""):
        self._runtime = runtime
        self._task_desc = task_desc
        self._items: list[ObjectRef] = []
        self._cursor = 0
        self._done = False
        self._cv = threading.Condition()

    # producer side (runtime)
    def _append(self, ref: ObjectRef) -> None:
        with self._cv:
            self._items.append(ref)
            self._cv.notify_all()

    def _finish(self) -> None:
        with self._cv:
            self._done = True
            self._cv.notify_all()

    # consumer side (single shared cursor: __iter__ and next_ready compose)
    def __iter__(self):
        while True:
            item = self.next_ready()
            if item is None:
                return
            yield item

    def next_ready(self, timeout: Optional[float] = None) -> Optional[ObjectRef]:
        with self._cv:
            while self._cursor >= len(self._items) and not self._done:
                if not self._cv.wait(timeout):
                    return None
            if self._cursor < len(self._items):
                item = self._items[self._cursor]
                self._cursor += 1
                return item
            return None
