"""Actor runtime: stateful workers with ordered mailboxes.

Analog of the reference's actor machinery (GcsActorManager
src/ray/gcs/gcs_server/gcs_actor_manager.h:324 for lifecycle,
ActorTaskSubmitter src/ray/core_worker/transport/actor_task_submitter.h:75
for ordered delivery, ConcurrencyGroupManager + fiber.h for async actors).
TPU-first simplification: actors are threads (or asyncio tasks) inside the
host JAX process, so "submission order == execution order" falls out of a
FIFO mailbox rather than sequence-number resequencing over gRPC. Restart
semantics (`max_restarts`) re-run the constructor in a fresh mailbox.
"""

from __future__ import annotations

import asyncio
import inspect
import queue
import threading
import traceback
from typing import TYPE_CHECKING, Any, Optional

from ray_tpu.core import errors
from ray_tpu.core.scheduler import resolve_args
from ray_tpu.core.task import ActorOptions, TaskSpec
from ray_tpu.utils.ids import ActorID, ObjectID
from ray_tpu.utils.logging import get_logger

if TYPE_CHECKING:
    from ray_tpu.core.runtime import Runtime

logger = get_logger("ray_tpu.actors")

_KILL = object()  # mailbox sentinel


class ActorState:
    PENDING = "PENDING_CREATION"
    ALIVE = "ALIVE"
    RESTARTING = "RESTARTING"
    DEAD = "DEAD"


class Actor:
    """Server side of one actor: instance + mailbox + executor thread(s)."""

    def __init__(
        self,
        runtime: "Runtime",
        actor_id: ActorID,
        cls: type,
        ctor_args: tuple,
        ctor_kwargs: dict,
        options: ActorOptions,
    ):
        self.runtime = runtime
        self.actor_id = actor_id
        self.cls = cls
        self.ctor_args = ctor_args
        self.ctor_kwargs = ctor_kwargs
        self.options = options
        self.state = ActorState.PENDING
        self.instance: Any = None
        self.death_cause: Optional[BaseException] = None
        self.restarts_used = 0
        self.num_handles = 1
        # set by ActorClass.remote after construction; released once on death
        self._resource_pool = None
        self._resource_req = None
        self._resources_released = False
        self._mailbox: queue.Queue = queue.Queue()
        self._is_async = any(
            inspect.iscoroutinefunction(m) or inspect.isasyncgenfunction(m)
            for _, m in inspect.getmembers(cls, inspect.isfunction)
        )
        self._lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._main,
            args=(self._mailbox,),
            name=f"ray_tpu-actor-{actor_id.hex()[:8]}",
            daemon=True,
        )
        self._thread.start()

    # -- lifecycle -----------------------------------------------------------

    def _construct(self) -> bool:
        try:
            args, kwargs = resolve_args(self.runtime, self.ctor_args, self.ctor_kwargs)
            self.instance = self.cls(*args, **kwargs)
            self.state = ActorState.ALIVE
            return True
        except BaseException as e:  # noqa: BLE001
            self.death_cause = errors.TaskError(
                e, traceback.format_exc(), f"{self.cls.__name__}.__init__"
            )
            self.state = ActorState.DEAD
            return False

    def _main(self, mailbox: queue.Queue) -> None:
        if not self._construct():
            self._drain_dead(mailbox)
            return
        if self._is_async:
            self._async_main(mailbox)
        else:
            self._sync_main(mailbox)
        self._drain_dead(mailbox)

    def _stale(self, mailbox: queue.Queue) -> bool:
        """True if this thread's mailbox was swapped out by a restart."""
        with self._lock:
            # the restart path (kill with restarts left) swaps _mailbox
            # under _lock; an unlocked read here could let a dying
            # incarnation mark the RESTARTED actor DEAD in _drain_dead
            return mailbox is not self._mailbox

    def _sync_main(self, mailbox: queue.Queue) -> None:
        conc = max(1, self.options.max_concurrency)
        if conc == 1:
            while True:
                item = mailbox.get()
                if item is _KILL:
                    break
                self._execute(item)
        else:
            import concurrent.futures

            with concurrent.futures.ThreadPoolExecutor(max_workers=conc) as pool:
                while True:
                    item = mailbox.get()
                    if item is _KILL:
                        break
                    pool.submit(self._execute, item)

    def _async_main(self, mailbox: queue.Queue) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        sem = asyncio.Semaphore(max(1, self.options.max_concurrency or 1000))

        async def runner():
            while True:
                item = await loop.run_in_executor(None, mailbox.get)
                if item is _KILL:
                    return
                asyncio.ensure_future(self._execute_async(item, sem))

        try:
            loop.run_until_complete(runner())
            pending = asyncio.all_tasks(loop)
            for t in pending:
                t.cancel()
            if pending:
                # let cancellations actually run so in-flight calls deliver
                # ActorDiedError instead of hanging their callers
                loop.run_until_complete(
                    asyncio.gather(*pending, return_exceptions=True)
                )
        finally:
            loop.close()

    def _drain_dead(self, mailbox: queue.Queue) -> None:
        """After this mailbox's actor incarnation ends: fail queued work."""
        if not self._stale(mailbox):
            self.state = ActorState.DEAD
            self._release_resources()
        while True:
            try:
                item = mailbox.get_nowait()
            except queue.Empty:
                return
            if item is _KILL:
                continue
            self._fail(item, self._died_error())

    def _died_error(self) -> BaseException:
        return errors.ActorDiedError(
            f"actor {self.cls.__name__}[{self.actor_id.hex()[:8]}] is dead"
            + (f": {self.death_cause}" if self.death_cause else "")
        )

    # -- execution -----------------------------------------------------------

    def _framework_method(self, name: str):
        """Framework-injected actor methods (run on the actor's own executor
        thread so thread-local state lands in the right place)."""
        if name == "__ray_tpu_collective_init__":
            from ray_tpu.collective.collective import init_collective_group

            return lambda world, rank, backend, group, gen=0: init_collective_group(
                world, rank, backend=backend, group_name=group, gen=gen
            )
        if name == "__ray_tpu_dag_exec_loop__":
            from ray_tpu.dag.compiled import _actor_exec_loop

            return lambda plan, input_source: _actor_exec_loop(
                self.instance, plan, input_source
            )
        return None

    def _execute(self, spec: TaskSpec) -> None:
        # caller's context restored around execution: actor-task events
        # carry the trace, and user code in the method inherits it
        # (nested calls, obs.span blocks, serve replicas)
        from ray_tpu.obs import context as trace_context

        with trace_context.use_from(spec.trace):
            return self._execute_body(spec)

    def _execute_body(self, spec: TaskSpec) -> None:
        from ray_tpu.core.events import TaskState

        self.runtime.task_events.record(
            spec.task_id, spec.describe(), TaskState.RUNNING,
            kind="actor_task", actor_id=self.actor_id,
        )
        try:
            args, kwargs = resolve_args(self.runtime, spec.args, spec.kwargs)
            method = self._framework_method(spec.method_name) or getattr(
                self.instance, spec.method_name
            )
            if spec.streaming:
                from ray_tpu.core.scheduler import _execute_streaming

                _execute_streaming(self.runtime, spec, args, kwargs, fn=method)
                return
            result = method(*args, **kwargs)
        except BaseException as e:  # noqa: BLE001
            self._fail(
                spec, errors.TaskError(e, traceback.format_exc(), spec.describe())
            )
            return
        self._store(spec, result)

    async def _execute_async(self, spec: TaskSpec, sem: asyncio.Semaphore) -> None:
        # contextvar set inside the coroutine is task-local (asyncio
        # copies the context per task), so concurrent calls don't leak
        from ray_tpu.obs import context as trace_context

        with trace_context.use_from(spec.trace):
            await self._execute_async_body(spec, sem)

    async def _execute_async_body(self, spec: TaskSpec, sem: asyncio.Semaphore) -> None:
        from ray_tpu.core.events import TaskState

        async with sem:
            self.runtime.task_events.record(
                spec.task_id, spec.describe(), TaskState.RUNNING,
                kind="actor_task", actor_id=self.actor_id,
            )
            try:
                args, kwargs = resolve_args(self.runtime, spec.args, spec.kwargs)
                method = self._framework_method(spec.method_name) or getattr(
                    self.instance, spec.method_name
                )
                if spec.streaming:
                    await self._stream_async(spec, method, args, kwargs)
                    return
                result = method(*args, **kwargs)
                if inspect.isawaitable(result):
                    result = await result
            except asyncio.CancelledError:
                # actor killed while this call was in flight
                self._fail(spec, self._died_error())
                raise
            except BaseException as e:  # noqa: BLE001
                self._fail(
                    spec, errors.TaskError(e, traceback.format_exc(), spec.describe())
                )
                return
            self._store(spec, result)

    async def _stream_async(self, spec: TaskSpec, method, args, kwargs) -> None:
        from ray_tpu.core.events import TaskState
        from ray_tpu.core.ref import ObjectRef

        gen = self.runtime.streaming_generators.get(spec.task_id)
        failure = None
        try:
            it = method(*args, **kwargs)
            i = 0
            if hasattr(it, "__aiter__"):
                async for item in it:
                    obj_id = ObjectID.for_task_return(spec.task_id, i + 1)
                    self.runtime.object_store.put(obj_id, item)
                    if gen is not None:
                        gen._append(ObjectRef(obj_id, self.runtime, spec.describe()))
                    i += 1
            else:
                for item in it:
                    obj_id = ObjectID.for_task_return(spec.task_id, i + 1)
                    self.runtime.object_store.put(obj_id, item)
                    if gen is not None:
                        gen._append(ObjectRef(obj_id, self.runtime, spec.describe()))
                    i += 1
        except BaseException as e:  # noqa: BLE001
            failure = repr(e)
            err = errors.TaskError(e, traceback.format_exc(), spec.describe())
            if gen is not None:
                obj_id = ObjectID.for_task_return(spec.task_id, 0)
                self.runtime.object_store.put_error(obj_id, err)
                gen._append(ObjectRef(obj_id, self.runtime, spec.describe()))
        finally:
            if gen is not None:
                gen._finish()
            self.runtime.streaming_generators.pop(spec.task_id, None)
            self.runtime.on_task_finished(spec)
            self.runtime.task_events.record(
                spec.task_id, spec.describe(),
                TaskState.FAILED if failure else TaskState.FINISHED,
                kind="actor_task", actor_id=self.actor_id, error=failure,
            )

    def _store(self, spec: TaskSpec, result) -> None:
        from ray_tpu.core.events import TaskState
        from ray_tpu.core.scheduler import _store_results

        _store_results(self.runtime, spec, result)
        self.runtime.on_task_finished(spec)
        self.runtime.task_events.record(
            spec.task_id, spec.describe(), TaskState.FINISHED,
            kind="actor_task", actor_id=self.actor_id,
        )

    def _fail(self, spec: TaskSpec, err: BaseException) -> None:
        from ray_tpu.core.events import TaskState

        for rid in spec.return_ids:
            self.runtime.object_store.put_error(rid, err)
        self.runtime.on_task_finished(spec)
        self.runtime.task_events.record(
            spec.task_id, spec.describe(), TaskState.FAILED,
            kind="actor_task", actor_id=self.actor_id, error=repr(err),
        )

    def _release_resources(self) -> None:
        with self._lock:
            if self._resources_released or self._resource_pool is None:
                return
            self._resources_released = True
        self._resource_pool.release(self._resource_req)
        self.runtime.scheduler.notify()

    # -- client side ---------------------------------------------------------

    def submit(self, spec: TaskSpec) -> None:
        with self._lock:
            if self.state == ActorState.DEAD:
                self._fail(spec, self._died_error())
                return
            self._mailbox.put(spec)

    def kill(self, no_restart: bool = True) -> None:
        with self._lock:
            if self.state == ActorState.DEAD:
                return
            if not no_restart and self.restarts_used < self.options.max_restarts:
                self.restarts_used += 1
                self.state = ActorState.RESTARTING
                old_thread = self._thread
                self._mailbox.put(_KILL)
                # fresh mailbox + thread re-running the constructor
                self._mailbox = queue.Queue()
                self._thread = threading.Thread(
                    target=self._main,
                    args=(self._mailbox,),
                    name=f"ray_tpu-actor-{self.actor_id.hex()[:8]}-r{self.restarts_used}",
                    daemon=True,
                )
                self._thread.start()
                return
            self.state = ActorState.DEAD
            self.death_cause = errors.ActorDiedError("killed via ray_tpu.kill")
            self._mailbox.put(_KILL)
