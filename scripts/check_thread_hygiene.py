#!/usr/bin/env python
"""Thread-hygiene lint (CI gate, imported as a tier-1 test).

Every ``threading.Thread(...)`` in the scanned packages (plus
``benchmarks/``) must set ``daemon=True`` or be joined on a reachable
shutdown path in the same file — a leaked non-daemon thread outlives
``main()``. Rules + allowlist: ``ray_tpu/analysis/thread_hygiene.py``.

Run standalone: ``python scripts/check_thread_hygiene.py``
(exit 1 on problems).
"""

from __future__ import annotations

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from ray_tpu.analysis.thread_hygiene import (  # noqa: E402,F401 — re-exported
    ALLOWLIST,
    SCAN_PACKAGES,
    check_model,
    collect_violations,
)


def main() -> int:
    problems = collect_violations()
    if problems:
        print(f"check_thread_hygiene: {len(problems)} problem(s)")
        for p in problems:
            print(f"  {p}")
        return 1
    print("check_thread_hygiene: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
