#!/usr/bin/env python
"""Static metrics-registry lint (CI gate, imported as a tier-1 test).

Thin CLI shim: the lint lives in ``ray_tpu/analysis/metrics_registry.py``
under the shared analysis umbrella. Verdict strings are unchanged from
the pre-framework version; see that module's docstring for the rules.

Run standalone: ``python scripts/check_metrics.py`` (exit 1 on problems).
"""

from __future__ import annotations

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from ray_tpu.analysis.metrics_registry import (  # noqa: E402,F401 — re-exported
    INSTRUMENTED,
    check_aggregations,
    check_registry,
    main,
    register_instrumented_metrics,
    run_check,
)

if __name__ == "__main__":
    sys.exit(main())
