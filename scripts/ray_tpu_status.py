#!/usr/bin/env python
"""`ray_tpu status` — one-look cluster health from ONE GCS query.

Prints nodes (liveness, resources, telemetry staleness), serve pools
(role-tagged replica counts), fleet utilization (KV-page occupancy, HBM
bytes, queue depth, KV-transfer bytes/s, spec-decode acceptance), and
per-model-tag SLO grades computed from the GCS-merged TTFT/TPOT/
queue-wait histograms (ray_tpu.obs.telemetry).

Usage:
    python scripts/ray_tpu_status.py --gcs HOST:PORT [--json]
        [--ttft S] [--tpot S] [--queue-wait S]

The whole report comes from the single ``telemetry_status`` RPC — the
CLI works against any live GCS, including one whose nodes are partitioned
(they show up as stale, not absent).
"""

from __future__ import annotations

import argparse
import json
import sys


def fetch_status(gcs: str, thresholds=None, timeout: float = 10.0) -> dict:
    from ray_tpu.cluster.rpc import RpcClient

    host, port = gcs.rsplit(":", 1)
    client = RpcClient(host, int(port), timeout=timeout).connect(retries=2)
    try:
        return client.call(
            "telemetry_status",
            {"thresholds": thresholds} if thresholds else {},
            timeout=timeout,
        )
    finally:
        client.close()


def render_status(gcs: str, thresholds=None) -> str:
    from ray_tpu.obs.telemetry import format_status

    return format_status(fetch_status(gcs, thresholds))


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--gcs", required=True, help="GCS address host:port")
    p.add_argument("--json", action="store_true",
                   help="dump the raw status payload instead of the table")
    p.add_argument("--ttft", type=float, default=None,
                   help="green TTFT threshold (s) at the SLO percentile")
    p.add_argument("--tpot", type=float, default=None,
                   help="green TPOT threshold (s)")
    p.add_argument("--queue-wait", type=float, default=None,
                   help="green queue-wait threshold (s)")
    p.add_argument("--percentile", type=float, default=None,
                   help="SLO percentile (default 95)")
    args = p.parse_args()
    thresholds = {}
    if args.ttft is not None:
        thresholds["ttft_p_s"] = args.ttft
    if args.tpot is not None:
        thresholds["tpot_p_s"] = args.tpot
    if args.queue_wait is not None:
        thresholds["queue_wait_p_s"] = args.queue_wait
    if args.percentile is not None:
        thresholds["percentile"] = args.percentile
    try:
        report = fetch_status(args.gcs, thresholds or None)
    except Exception as e:  # noqa: BLE001 — CLI surface
        print(f"ray_tpu status: cannot reach GCS at {args.gcs}: {e}",
              file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(report, indent=2, default=str))
        return 0
    from ray_tpu.obs.telemetry import format_status

    print(format_status(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
