#!/usr/bin/env python
"""Lock-guard inference lint (CI gate, imported as a tier-1 test).

Infers which ``threading`` lock guards which ``self._*`` attribute from
``with self._lock:`` bodies across ray_tpu's threaded planes, then flags
reads/mutations of a majority-guarded attribute outside any acquisition
of that lock. Rules + allowlist: ``ray_tpu/analysis/lock_guards.py``.

Run standalone: ``python scripts/check_lock_guards.py`` (exit 1 on problems).
"""

from __future__ import annotations

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from ray_tpu.analysis.lock_guards import (  # noqa: E402,F401 — re-exported
    ALLOWLIST,
    check_model,
    collect_violations,
    infer_guards,
)


def main() -> int:
    problems = collect_violations()
    if problems:
        print(f"check_lock_guards: {len(problems)} problem(s)")
        for p in problems:
            print(f"  {p}")
        return 1
    print("check_lock_guards: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
