#!/usr/bin/env python
"""Static blocking-call timeout lint (CI gate, imported as a tier-1 test).

Thin CLI shim: the linter lives in ``ray_tpu/analysis/timeouts.py`` on
the shared analysis framework (walker + allowlist with stale-entry
detection). Verdict strings are unchanged from the pre-framework
version; see that module's docstring for the rules.

Run standalone: ``python scripts/check_timeouts.py`` (exit 1 on problems).
"""

from __future__ import annotations

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from ray_tpu.analysis.timeouts import (  # noqa: E402,F401 — re-exported API
    ALLOWLIST,
    BOUNDED_PARK_MIN_ARGS,
    PARK_CALLS,
    RECV_CALLS,
    SCAN_DIRS,
    collect_violations,
    lint_source,
    main,
)

if __name__ == "__main__":
    sys.exit(main())
