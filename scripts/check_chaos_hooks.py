#!/usr/bin/env python
"""Chaos-coverage lint (CI gate, imported as a tier-1 test).

Every ``FaultKind`` declared in ``ray_tpu/chaos/schedule.py`` must have
at least one firing site (an in-process ``fire(...)`` hook naming it or
a runner executor branch) AND at least one test referencing it — a dead
fault kind is untested robustness. Logic:
``ray_tpu/analysis/chaos_coverage.py``.

Run standalone: ``python scripts/check_chaos_hooks.py`` (exit 1 on problems).
"""

from __future__ import annotations

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from ray_tpu.analysis.chaos_coverage import (  # noqa: E402,F401 — re-exported
    collect_violations,
    declared_kinds,
    firing_sites,
    test_references,
)


def main() -> int:
    problems = collect_violations()
    if problems:
        print(f"check_chaos_hooks: {len(problems)} problem(s)")
        for p in problems:
            print(f"  {p}")
        return 1
    kinds = declared_kinds()
    print(f"check_chaos_hooks: ok ({len(kinds)} fault kinds fired + tested)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
