#!/usr/bin/env python
"""Lock-order deadlock lint (CI gate, imported as a tier-1 test).

Builds the global lock-acquisition graph (nested ``with`` plus one hop
through self-method calls) over ray_tpu's threaded planes and fails on
cycles and non-reentrant self-acquisitions — the deadlocks chaos only
finds by luck. Rules + allowlist: ``ray_tpu/analysis/lock_order.py``.

Run standalone: ``python scripts/check_lock_order.py`` (exit 1 on problems).
"""

from __future__ import annotations

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from ray_tpu.analysis.lock_order import (  # noqa: E402,F401 — re-exported
    ALLOWLIST,
    build_edges,
    check_model,
    collect_violations,
)


def main() -> int:
    problems = collect_violations()
    if problems:
        print(f"check_lock_order: {len(problems)} problem(s)")
        for p in problems:
            print(f"  {p}")
        return 1
    print("check_lock_order: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
