#!/usr/bin/env python
"""Perf regression gate over the capture ledger (CI gate, imported as a
tier-1 test). Thin CLI shim — the framework lives in
ray_tpu/analysis/perf_gate.py.

    python scripts/check_perf.py                       # ledger integrity
    python scripts/check_perf.py --capture fresh.json  # gate a fresh capture
"""

from __future__ import annotations

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from ray_tpu.analysis.perf_gate import (  # noqa: E402,F401 — re-exported API
    GateResult,
    evaluate_capture,
    gate_capture,
    main,
    run_check,
)

if __name__ == "__main__":
    sys.exit(main())
