#!/usr/bin/env python
"""Umbrella runner for every static-analysis pass: one exit code,
per-pass summary, ``--json`` for machines.

    python scripts/lint_all.py            # human summary, exit 1 on any fail
    python scripts/lint_all.py --json     # {"passes": {...}, "ok": bool}

Individual passes remain runnable standalone (scripts/check_*.py) and
are each imported as a tier-1 test; this runner exists for pre-commit /
CI convenience and for `ray_tpu status`-style tooling to shell out to.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def _passes():
    """(name, thunk) pairs, cheap AST passes first, the live-registry
    lint (heavy imports) last."""
    from ray_tpu.analysis import (
        blocking,
        chaos_coverage,
        lock_guards,
        lock_order,
        thread_hygiene,
        timeouts,
    )
    return [
        ("check_timeouts", timeouts.collect_violations),
        ("check_lock_guards", lock_guards.collect_violations),
        ("check_lock_order", lock_order.collect_violations),
        ("check_blocking_under_lock", blocking.collect_violations),
        ("check_chaos_hooks", chaos_coverage.collect_violations),
        ("check_thread_hygiene", thread_hygiene.collect_violations),
        ("check_metrics", _run_metrics),
        ("check_perf", _run_perf),
    ]


def _run_metrics() -> list[str]:
    from ray_tpu.analysis import metrics_registry

    return metrics_registry.run_check()


def _run_perf() -> list[str]:
    from ray_tpu.analysis import perf_gate

    return perf_gate.run_check()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    args = ap.parse_args(argv)

    results: dict[str, list[str]] = {}
    for name, thunk in _passes():
        try:
            results[name] = thunk()
        except Exception as e:  # noqa: BLE001 — a crashed pass is a failure
            results[name] = [f"{name}: pass crashed: {e!r}"]

    ok = all(not v for v in results.values())
    if args.as_json:
        print(json.dumps({
            "ok": ok,
            "passes": {
                name: {"ok": not v, "problems": v}
                for name, v in results.items()
            },
        }, indent=2))
    else:
        for name, v in results.items():
            status = "ok" if not v else f"{len(v)} problem(s)"
            print(f"{name}: {status}")
            for p in v:
                print(f"  {p}")
        print("lint_all:", "ok" if ok else "FAILED")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
