#!/usr/bin/env python
"""Blocking-call-under-lock lint (CI gate, imported as a tier-1 test).

Flags RPC sends, socket recvs, sleeps, joins, ``kv_wait`` parks, and
chaos-hook ``fire`` sites executed while holding a lock: a stalled peer
must never stall every other caller of that lock. Condition waits on
their own lock are exempt (waiting releases it). Rules + allowlist:
``ray_tpu/analysis/blocking.py``.

Run standalone: ``python scripts/check_blocking_under_lock.py``
(exit 1 on problems).
"""

from __future__ import annotations

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from ray_tpu.analysis.blocking import (  # noqa: E402,F401 — re-exported
    ALLOWLIST,
    BLOCKING_CALLS,
    check_model,
    collect_violations,
)


def main() -> int:
    problems = collect_violations()
    if problems:
        print(f"check_blocking_under_lock: {len(problems)} problem(s)")
        for p in problems:
            print(f"  {p}")
        return 1
    print("check_blocking_under_lock: ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
